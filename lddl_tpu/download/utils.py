"""Shared downloader helpers.

Reference parity: lddl/download/utils.py:30-51. The output contract every
downloader must produce (consumed by lddl_tpu.preprocess.readers):
``<outdir>/source/<i>.txt`` with ONE document per line whose first
whitespace token is the document id.
"""

import os
import sys
import urllib.request


def safe_extractall(tf, outdir):
    """tarfile.extractall with the 'data' safety filter where available
    (the filter kwarg only exists from Python 3.10.12 / 3.11.4 / 3.12).
    On older interpreters, members are validated by hand first — the
    fallback must not reintroduce tar path traversal."""
    try:
        tf.extractall(outdir, filter="data")
        return
    except TypeError:
        pass
    base = os.path.realpath(outdir)
    for m in tf.getmembers():
        target = os.path.realpath(os.path.join(base, m.name))
        if target != base and not target.startswith(base + os.sep):
            raise ValueError("unsafe tar member path: {}".format(m.name))
        if m.issym() or m.islnk():
            link = os.path.realpath(
                os.path.join(os.path.dirname(target), m.linkname))
            if link != base and not link.startswith(base + os.sep):
                raise ValueError(
                    "unsafe tar link target: {} -> {}".format(
                        m.name, m.linkname))
        if m.isdev():
            raise ValueError("device node in tar: {}".format(m.name))
    tf.extractall(outdir)


def download(url, path, chunk_size=16 * 1024 * 1024, progress=True):
    """Streaming HTTP(S) download to ``path`` (stdlib only — TPU pods often
    lack requests/tqdm; zero-egress environments get a clear error)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    try:
        with urllib.request.urlopen(url) as r, open(path, "wb") as f:
            total = r.headers.get("Content-Length")
            total = int(total) if total else None
            done = 0
            while True:
                chunk = r.read(chunk_size)
                if not chunk:
                    break
                f.write(chunk)
                done += len(chunk)
                if progress:
                    pct = " {:.1f}%".format(100 * done / total) if total else ""
                    sys.stderr.write("\r{} {:,} bytes{}".format(
                        os.path.basename(path), done, pct))
            if progress:
                sys.stderr.write("\n")
    except OSError as e:
        raise RuntimeError(
            "download of {} failed ({}); if this environment has no "
            "egress, fetch the archive elsewhere and pass it via the "
            "--local-* flag".format(url, e)) from e
    return path


class _ShardWriter:
    """Writes documents round-robin into ``<outdir>/source/<i>.txt``."""

    def __init__(self, outdir, num_shards, prefix=""):
        # ``prefix`` namespaces shard files (e.g. per language) so multiple
        # passes into one outdir never truncate each other's shards.
        self._dir = os.path.join(outdir, "source")
        os.makedirs(self._dir, exist_ok=True)
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self._files = [
            open(os.path.join(self._dir, "{}{}.txt".format(prefix, i)), "w",
                 encoding="utf-8") for i in range(num_shards)
        ]
        self._count = 0

    def write(self, doc_id, text):
        # One line per document; newlines inside the doc flatten to spaces.
        text = " ".join(text.split())
        if not text:
            return
        if any(c.isspace() for c in doc_id):
            raise ValueError("doc id may not contain whitespace: "
                             "{!r}".format(doc_id))
        f = self._files[self._count % len(self._files)]
        f.write(doc_id + " " + text + "\n")
        self._count += 1

    def close(self):
        for f in self._files:
            f.close()

    @property
    def num_documents(self):
        return self._count


def shard_documents(docs, outdir, num_shards):
    """docs: iterable of (doc_id, text) -> source shards; returns count."""
    writer = _ShardWriter(outdir, num_shards)
    try:
        for doc_id, text in docs:
            writer.write(doc_id, text)
    finally:
        writer.close()
    return writer.num_documents
