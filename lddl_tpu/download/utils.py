"""Shared downloader helpers.

Reference parity: lddl/download/utils.py:30-51. The output contract every
downloader must produce (consumed by lddl_tpu.preprocess.readers):
``<outdir>/source/<i>.txt`` with ONE document per line whose first
whitespace token is the document id.
"""

import os
import sys
import urllib.request

from ..utils.cpus import usable_cpu_count


def safe_extractall(tf, outdir):
    """tarfile.extractall with the 'data' safety filter where available
    (the filter kwarg only exists from Python 3.10.12 / 3.11.4 / 3.12).
    On older interpreters, members are validated by hand first — the
    fallback must not reintroduce tar path traversal."""
    try:
        tf.extractall(outdir, filter="data")
        return
    except TypeError:
        pass
    base = os.path.realpath(outdir)
    for m in tf.getmembers():
        target = os.path.realpath(os.path.join(base, m.name))
        if target != base and not target.startswith(base + os.sep):
            raise ValueError("unsafe tar member path: {}".format(m.name))
        if m.issym():
            # Symlink targets resolve relative to the member's directory.
            link = os.path.realpath(
                os.path.join(os.path.dirname(target), m.linkname))
            if link != base and not link.startswith(base + os.sep):
                raise ValueError(
                    "unsafe tar link target: {} -> {}".format(
                        m.name, m.linkname))
        elif m.islnk():
            # Hardlink targets resolve relative to the archive root, like
            # the 'data' filter does.
            link = os.path.realpath(os.path.join(base, m.linkname))
            if link != base and not link.startswith(base + os.sep):
                raise ValueError(
                    "unsafe tar hardlink target: {} -> {}".format(
                        m.name, m.linkname))
        elif not (m.isfile() or m.isdir()):
            # FIFOs, device nodes, and any other special member types are
            # rejected, matching filter="data".
            raise ValueError(
                "special tar member type rejected: {}".format(m.name))
    tf.extractall(outdir)


def download(url, path, chunk_size=16 * 1024 * 1024, progress=True):
    """Streaming HTTP(S) download to ``path`` (stdlib only — TPU pods often
    lack requests/tqdm; zero-egress environments get a clear error)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    try:
        with urllib.request.urlopen(url) as r, open(path, "wb") as f:
            total = r.headers.get("Content-Length")
            total = int(total) if total else None
            done = 0
            while True:
                chunk = r.read(chunk_size)
                if not chunk:
                    break
                f.write(chunk)
                done += len(chunk)
                if progress:
                    pct = " {:.1f}%".format(100 * done / total) if total else ""
                    sys.stderr.write("\r{} {:,} bytes{}".format(
                        os.path.basename(path), done, pct))
            if progress:
                sys.stderr.write("\n")
    except OSError as e:
        raise RuntimeError(
            "download of {} failed ({}); if this environment has no "
            "egress, fetch the archive elsewhere and pass it via the "
            "--local-* flag".format(url, e)) from e
    return path


def format_doc_line(doc_id, text):
    """One source line per document: ``<id> <flattened text>\\n``; None when
    the text is empty after newline flattening."""
    text = " ".join(text.split())
    if not text:
        return None
    if any(c.isspace() for c in doc_id):
        raise ValueError("doc id may not contain whitespace: "
                         "{!r}".format(doc_id))
    return doc_id + " " + text + "\n"


class _ShardWriter:
    """Writes documents round-robin into ``<outdir>/source/<i>.txt``."""

    def __init__(self, outdir, num_shards, prefix=""):
        # ``prefix`` namespaces shard files (e.g. per language) so multiple
        # passes into one outdir never truncate each other's shards.
        self._dir = os.path.join(outdir, "source")
        os.makedirs(self._dir, exist_ok=True)
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self._files = [
            open(os.path.join(self._dir, "{}{}.txt".format(prefix, i)), "w",
                 encoding="utf-8") for i in range(num_shards)
        ]
        self._count = 0

    def write(self, doc_id, text):
        line = format_doc_line(doc_id, text)
        if line is None:
            return
        f = self._files[self._count % len(self._files)]
        f.write(line)
        self._count += 1

    def close(self):
        for f in self._files:
            f.close()

    @property
    def num_documents(self):
        return self._count


def _write_shard_from_files(shard_path, input_paths, parse_fn):
    """Build ONE shard file from its assigned input files; returns the
    document count. Top-level so process pools can pickle it."""
    count = 0
    with open(shard_path, "w", encoding="utf-8") as f:
        for path in input_paths:
            for doc_id, text in parse_fn(path):
                line = format_doc_line(doc_id, text)
                if line is not None:
                    f.write(line)
                    count += 1
    return count


def shard_files_parallel(input_paths, outdir, num_shards, parse_fn,
                         num_processes=None, prefix=""):
    """Reference-style parallel sharding (ref lddl/download/wikipedia.py:
    77-85, books.py:177-187): input files are assigned round-robin to
    shards and a process pool builds each shard file independently —
    shard k = parse of ``input_paths[k::num_shards]``.

    ``parse_fn(path)`` must be a picklable top-level callable yielding
    (doc_id, text) pairs. Returns the total document count.
    """
    source_dir = os.path.join(outdir, "source")
    os.makedirs(source_dir, exist_ok=True)
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    input_paths = sorted(input_paths)
    if len(input_paths) < num_shards:
        # Same behavior as the reference (empty shards are written), but
        # say so: downstream block planning sees zero-byte inputs.
        sys.stderr.write(
            "warning: {} input files into {} shards leaves {} shard "
            "file(s) empty; consider --num-shards <= input file count\n"
            .format(len(input_paths), num_shards,
                    num_shards - len(input_paths)))
    shards = [
        (os.path.join(source_dir, "{}{}.txt".format(prefix, k)),
         input_paths[k::num_shards])
        for k in range(num_shards)
    ]
    if num_processes is None or num_processes == 0:
        num_processes = usable_cpu_count()
    num_processes = min(num_processes, num_shards)
    if num_processes <= 1:
        return sum(_write_shard_from_files(p, fps, parse_fn)
                   for p, fps in shards)
    import concurrent.futures
    import multiprocessing
    with concurrent.futures.ProcessPoolExecutor(
            max_workers=num_processes,
            mp_context=multiprocessing.get_context("spawn")) as pool:
        return sum(pool.map(_write_shard_from_files,
                            *zip(*[(p, fps, parse_fn) for p, fps in shards])))


def shard_documents(docs, outdir, num_shards):
    """docs: iterable of (doc_id, text) -> source shards; returns count."""
    writer = _ShardWriter(outdir, num_shards)
    try:
        for doc_id, text in docs:
            writer.write(doc_id, text)
    finally:
        writer.close()
    return writer.num_documents
