"""Wikipedia downloader: dump -> wikiextractor -> one-article-per-line shards.

Reference parity: lddl/download/wikipedia.py. Three skippable steps:
(1) download ``<lang>wiki-latest-pages-articles.xml.bz2``;
(2) extract article text with the external ``wikiextractor`` package;
(3) aggregate the extracted ``<doc ...>`` XML-ish files into
    ``source/<i>.txt`` shards, one article per line, id ``wiki-<id>``,
    title dropped (ref: wikipedia.py:48-85).

Each step gates its external dependency with a clear error and accepts
pre-staged inputs (``--local-dump``, ``--extracted-dir``) so offline
environments can run the later steps.
"""

import argparse
import os
import re
import subprocess
import sys

from ..utils.args import attach_bool_arg
from ..utils.fs import expand_outdir_and_mkdir, get_all_files_paths_under
from .utils import download, shard_files_parallel

_URLS = {
    "en": "https://dumps.wikimedia.org/enwiki/latest/enwiki-latest-pages-articles.xml.bz2",
    "zh": "https://dumps.wikimedia.org/zhwiki/latest/zhwiki-latest-pages-articles.xml.bz2",
}

_DOC_OPEN = re.compile(r'<doc id="([^"]+)"[^>]*>')


def parse_wikiextractor_file(path):
    """One wikiextractor output file -> (wiki-<id>, text) pairs. Articles
    open with ``<doc id=.. title=..>``, first content line repeats the
    title (dropped, ref wikipedia.py:60-66), and close with ``</doc>``."""
    with open(path, encoding="utf-8") as f:
        doc_id = None
        lines = []
        saw_title = False
        for raw in f:
            raw = raw.strip()
            m = _DOC_OPEN.match(raw)
            if m:
                doc_id = m.group(1)
                lines = []
                saw_title = False
                continue
            if raw == "</doc>":
                if doc_id is not None and lines:
                    yield "wiki-" + doc_id, " ".join(lines)
                doc_id = None
                continue
            if doc_id is None:
                continue
            if not saw_title:
                saw_title = True  # first line is the title: drop
                continue
            if raw:
                lines.append(raw)


def aggregate_extracted(extracted_dir, outdir, num_shards, prefix="",
                        num_processes=None):
    """wikiextractor output -> source shards, one pool worker per shard
    (ref: wikipedia.py:77-85)."""
    return shard_files_parallel(
        get_all_files_paths_under(extracted_dir), outdir, num_shards,
        parse_wikiextractor_file, num_processes=num_processes, prefix=prefix)


def run_wikiextractor(dump_path, extracted_dir):
    try:
        import wikiextractor  # noqa: F401
    except ImportError as e:
        raise RuntimeError(
            "the 'wikiextractor' package is required for the extract step "
            "(pip install wikiextractor), or pass --extracted-dir with "
            "pre-extracted output") from e
    subprocess.run(
        [sys.executable, "-m", "wikiextractor.WikiExtractor", dump_path,
         "--output", extracted_dir],
        check=True)


def attach_args(parser=None):
    parser = parser or argparse.ArgumentParser(
        description="Download Wikipedia and make one-article-per-line shards")
    parser.add_argument("--outdir", required=True)
    parser.add_argument("--langs", default="en",
                        help="comma-separated (en,zh)")
    parser.add_argument("--num-shards", type=int, default=256)
    parser.add_argument("--local-dump", default=None,
                        help="pre-downloaded .xml.bz2 (skips the download)")
    parser.add_argument("--extracted-dir", default=None,
                        help="pre-extracted wikiextractor output "
                             "(skips download+extract)")
    attach_bool_arg(parser, "download", default=True,
                    help_str="run the download step")
    attach_bool_arg(parser, "extract", default=True,
                    help_str="run the wikiextractor step")
    attach_bool_arg(parser, "shard", default=True,
                    help_str="run the sharding step")
    parser.add_argument("--number-of-sharding-processes", type=int, default=0,
                        help="process-pool size for the sharding step "
                             "(0 = cpu count)")
    return parser


def main(args=None):
    args = args if args is not None else attach_args().parse_args()
    outdir = expand_outdir_and_mkdir(args.outdir)
    for lang in args.langs.split(","):
        lang = lang.strip()
        if lang not in _URLS:
            raise ValueError("unsupported language {!r} (have {})".format(
                lang, sorted(_URLS)))
        dump_path = args.local_dump or os.path.join(
            outdir, "{}wiki-latest-pages-articles.xml.bz2".format(lang))
        if args.download and args.local_dump is None:
            download(_URLS[lang], dump_path)
        extracted = args.extracted_dir or os.path.join(outdir,
                                                       "extracted", lang)
        if args.extract and args.extracted_dir is None:
            run_wikiextractor(dump_path, extracted)
        if args.shard:
            # Per-language shard prefix: multiple --langs passes share one
            # outdir without overwriting each other.
            n = aggregate_extracted(
                extracted, outdir, args.num_shards, prefix=lang + "-",
                num_processes=args.number_of_sharding_processes)
            print("wikipedia[{}]: {} articles -> {} shards".format(
                lang, n, args.num_shards))


if __name__ == "__main__":
    main()
