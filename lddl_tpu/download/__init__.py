from .utils import download, shard_documents

__all__ = ["download", "shard_documents"]
