"""Device-mesh conventions.

The reference's only notion of topology is (rank, world_size) plus the
model-parallel fork's dp_rank (lddl/torch_mp/utils.py:33-51). TPU-native,
topology is a named ``jax.sharding.Mesh``; the loader derives everything it
needs (which samples this host must produce) from the mesh + batch sharding
instead of from NCCL collectives.

Canonical axis names used across lddl_tpu (a subset may be present):

    dp    data parallel          (batch dim)
    fsdp  fully-sharded DP       (batch dim + param shards)
    tp    tensor parallel        (hidden dims)
    sp    sequence/context par.  (sequence dim)
    pp    pipeline parallel      (layer stages; parallel/pipeline.py)

Batches are sharded over DATA_AXES = ('dp', 'fsdp'); all devices that share
the same (dp, fsdp) coordinate — i.e. TP/PP/SP peers — receive identical
data, which is exactly the reference's dp_rank contract
(lddl/torch_mp/bert.py:203-211).
"""

import numpy as np

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_PP = "pp"

# Mesh axes over which the global batch is sharded.
DATA_AXES = (AXIS_DP, AXIS_FSDP)


def make_mesh(axis_sizes, devices=None):
    """Build a Mesh from {axis_name: size}; size -1 means "absorb the rest".

    Axis order follows insertion order of ``axis_sizes``. Axes of size 1 are
    kept — a consistent rank makes sharding rules simpler to write.
    """
    # jax imported lazily: the offline pipeline stages (preprocess/balance)
    # must be importable on machines where jax is absent or broken.
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if n % known != 0:
            raise ValueError(
                "cannot infer -1 axis: {} devices not divisible by {}".format(
                    n, known))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(
            "mesh {} needs {} devices, have {}".format(
                dict(zip(names, sizes)), total, n))
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def data_axes_of(axis_names):
    """The data axes among ``axis_names``, in given order."""
    return tuple(a for a in axis_names if a in DATA_AXES)


def mesh_data_axes(mesh):
    """The data axes present in this mesh, in mesh order."""
    return data_axes_of(mesh.axis_names)


def data_parallel_size(mesh):
    """Number of data-parallel groups = product of data-axis sizes."""
    size = 1
    for a in mesh_data_axes(mesh):
        size *= mesh.shape[a]
    return size
