from .distributed import (
    Communicator,
    LocalCommunicator,
    JaxCommunicator,
    ThreadGroupCommunicator,
    get_communicator,
)
from .mesh import make_mesh, AXIS_DP, AXIS_FSDP, AXIS_TP, AXIS_SP, AXIS_PP, AXIS_EP, DATA_AXES

__all__ = [
    "Communicator",
    "LocalCommunicator",
    "JaxCommunicator",
    "ThreadGroupCommunicator",
    "get_communicator",
    "make_mesh",
    "AXIS_DP",
    "AXIS_FSDP",
    "AXIS_TP",
    "AXIS_SP",
    "AXIS_PP",
    "AXIS_EP",
    "DATA_AXES",
]
