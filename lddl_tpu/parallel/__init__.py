from .distributed import (
    Communicator,
    LocalCommunicator,
    JaxCommunicator,
    ThreadGroupCommunicator,
    get_communicator,
    node_info,
)
from .mesh import (make_mesh, AXIS_DP, AXIS_FSDP, AXIS_TP, AXIS_SP,
                   AXIS_PP, DATA_AXES)
from .pipeline import (make_pipelined_encoder, reference_encoder,
                       stack_layer_params, unstack_layer_params)

__all__ = [
    "Communicator",
    "LocalCommunicator",
    "JaxCommunicator",
    "ThreadGroupCommunicator",
    "get_communicator",
    "node_info",
    "make_mesh",
    "AXIS_DP",
    "AXIS_FSDP",
    "AXIS_TP",
    "AXIS_SP",
    "AXIS_PP",
    "DATA_AXES",
    "make_pipelined_encoder",
    "reference_encoder",
    "stack_layer_params",
    "unstack_layer_params",
]
