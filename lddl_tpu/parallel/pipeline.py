"""Minimal GPipe-style pipeline parallelism over the ``pp`` mesh axis.

The scaling-book recipe, TPU-native: encoder layers are stacked on a
leading axis and SHARDED over ``pp`` (each stage owns num_layers/pp
consecutive layers); inside ``shard_map`` every stage scans its local
layers and hands activations to the next stage with ``ppermute`` over the
ICI ring. Microbatches flow through the classic (n_micro + stages - 1)
schedule; autodiff through the whole thing gives pipelined backward for
free (XLA schedules the reverse ppermutes).

Scope: a complete, tested forward+backward pipeline step for the BERT
encoder stack (embeddings/heads replicated — they are a few percent of
FLOPs; layer params are the memory that matters). It demonstrates the
``pp`` axis end-to-end — mesh, loader dp-group derivation (pp peers get
identical batches), collectives — and is the template for a full
pipelined trainer. The reference has nothing comparable (its
model-parallel fork only reads dp_rank; lddl/torch_mp/utils.py:33-51).
"""

import functools

import numpy as np


def stack_layer_params(params, num_layers):
    """[layer_0..layer_{L-1}] param subtrees -> one tree with a leading
    [L, ...] axis per leaf (the pp-shardable layout)."""
    import jax

    layers = [params["layer_{}".format(i)] for i in range(num_layers)]
    return jax.tree.map(lambda *xs: np.stack(xs), *layers)


def unstack_layer_params(stacked, num_layers):
    import jax

    out = {}
    for i in range(num_layers):
        out["layer_{}".format(i)] = jax.tree.map(lambda x, i=i: x[i],
                                                 stacked)
    return out


def make_pipelined_encoder(mesh, cfg, n_micro):
    """Returns ``fn(stacked_layer_params, x, mask) -> y`` running the
    encoder stack as a pp-sharded GPipe pipeline.

    ``stacked_layer_params`` leaves are [num_layers, ...] (shard the
    leading axis over pp); ``x`` is [B, T, H] with B divisible by
    ``n_micro``; every stage sees the full batch replicated and the
    output is replicated again (last stage broadcasts).
    ``n_micro >= pp`` keeps every stage busy in steady state.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from . import compat
    from ..models.bert import EncoderLayer

    pp = mesh.shape["pp"]
    if cfg.num_layers % pp:
        raise ValueError("num_layers {} not divisible by pp {}".format(
            cfg.num_layers, pp))
    layer = EncoderLayer(cfg)

    def apply_local_stack(local_params, x, mask):
        # Scan this stage's layers over the leading local-layer axis.
        def body(h, layer_params):
            h = layer.apply({"params": layer_params}, h, mask, True)
            return h.astype(cfg.dtype), None

        y, _ = jax.lax.scan(body, x.astype(cfg.dtype), local_params)
        return y

    def stage_fn(local_params, x, mask):
        # local_params: [L/pp, ...] leaves; x: full [B, T, H] (replicated).
        stage = jax.lax.axis_index("pp")
        b = x.shape[0]
        mb = b // n_micro
        micro = x.reshape(n_micro, mb, *x.shape[1:])
        micro_mask = mask.reshape(n_micro, mb, *mask.shape[1:])

        n_steps = n_micro + pp - 1
        # Carries start pp-varying (pcast) in the kernel's dtype: the loop
        # body writes stage-dependent bf16 values into them.
        carry = compat.pcast(
            jnp.zeros(micro[0].shape, cfg.dtype), ("pp",), to="varying")
        outputs = compat.pcast(
            jnp.zeros(micro.shape, cfg.dtype), ("pp",), to="varying")

        def step(t, state):
            carry, outputs = state
            # Stage 0 injects microbatch t (while available); other stages
            # consume what arrived from the left neighbor.
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            inp = jnp.where(stage == 0, micro[feed_idx], carry)
            m_idx = jnp.clip(t - stage, 0, n_micro - 1)
            out = apply_local_stack(local_params, inp.astype(cfg.dtype),
                                    micro_mask[m_idx])
            # Last stage banks microbatch (t - pp + 1) when it's real.
            out_idx = jnp.clip(t - (pp - 1), 0, n_micro - 1)
            bank = (stage == pp - 1) & (t >= pp - 1)
            outputs = jnp.where(
                bank,
                outputs.at[out_idx].set(out),
                outputs)
            # Hand activations to the next stage (ring; the wrap-around
            # value into stage 0 is ignored — it injects fresh input).
            carry = jax.lax.ppermute(
                out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
            return carry, outputs

        carry, outputs = jax.lax.fori_loop(0, n_steps, step,
                                           (carry, outputs))
        # Broadcast the last stage's banked outputs to every stage so the
        # result is replicated over pp (heads/loss run replicated):
        # mask-and-psum (ppermute is a bijection, not a broadcast).
        outputs = jax.lax.psum(
            jnp.where(stage == pp - 1, outputs, jnp.zeros_like(outputs)),
            "pp")
        return outputs.reshape(b, *x.shape[1:])

    in_specs = (P("pp"), P(), P())
    out_specs = P()
    # check_vma=False: the epilogue's mask-and-psum DOES replicate the
    # output over pp, but the static varying-axis checker cannot infer
    # replication through a data-dependent mask + collective.
    fn = compat.shard_map(stage_fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    return fn


def reference_encoder(cfg):
    """The same stack, unpipelined (for equivalence tests)."""
    import jax

    from ..models.bert import EncoderLayer

    layer = EncoderLayer(cfg)

    def fn(stacked_layer_params, x, mask):
        def body(h, layer_params):
            h = layer.apply({"params": layer_params}, h, mask, True)
            return h.astype(cfg.dtype), None

        y, _ = jax.lax.scan(body, x.astype(cfg.dtype), stacked_layer_params)
        return y

    return fn
