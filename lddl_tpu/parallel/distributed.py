"""Host-level collective communication.

The reference coordinates hosts three different ways — mpi4py for the
balancer (lddl/dask/load_balance.py:210-223), torch.distributed NCCL for the
torch loaders (lddl/torch/utils.py:28-62), and Paddle env vars + a hand-built
static NCCL program for the paddle loader (lddl/paddle/utils.py:31-146).

TPU-native rebuild: ONE tiny communicator interface with pluggable backends.
The only collectives the whole pipeline needs are sum-allreduce over small
int64 vectors, max-allreduce, and a barrier — metadata sync, never tensor
transport (batches never cross hosts; each host feeds its own addressable
devices).

Backends:

- LocalCommunicator: world of 1; all ops are identity. The default.
- JaxCommunicator: multi-host via ``jax.distributed`` + on-device psum over
  whatever backend is initialized (TPU ICI/DCN, or CPU ring for
  preprocess-only clusters). Replaces MPI_Allreduce / MPI_Barrier.
- ThreadGroupCommunicator: N SPMD "ranks" as threads in one process, with
  real barrier semantics — used by the test-suite to exercise multi-rank
  lockstep algorithms (the fake multi-process harness the reference lacks,
  SURVEY.md §4).
"""

import threading

import numpy as np


class Communicator:
    """Interface. Ranks are 0..world_size-1."""

    @property
    def rank(self):
        raise NotImplementedError

    @property
    def world_size(self):
        raise NotImplementedError

    def barrier(self):
        raise NotImplementedError

    def allreduce_sum(self, values):
        """Element-wise sum of an int64 numpy vector across ranks."""
        raise NotImplementedError

    def allreduce_max(self, values):
        raise NotImplementedError


class LocalCommunicator(Communicator):

    @property
    def rank(self):
        return 0

    @property
    def world_size(self):
        return 1

    def barrier(self):
        pass

    def allreduce_sum(self, values):
        return np.array(values, dtype=np.int64, copy=True)

    def allreduce_max(self, values):
        return np.array(values, dtype=np.int64, copy=True)


class JaxCommunicator(Communicator):
    """Multi-host collectives over jax.distributed.

    Requires ``jax.distributed.initialize()`` to have been called (the CLIs
    do this when --multihost is passed). Works on TPU pods and on CPU-only
    preprocess clusters alike: the reduction rides whatever device backend
    is visible, and the payloads are tiny metadata vectors.
    """

    def __init__(self):
        import jax
        self._jax = jax
        if jax.process_count() <= 1:
            raise RuntimeError(
                "JaxCommunicator requires jax.distributed with >1 process; "
                "use LocalCommunicator for single-process runs")

    @property
    def rank(self):
        return self._jax.process_index()

    @property
    def world_size(self):
        return self._jax.process_count()

    def barrier(self):
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("lddl_tpu_barrier")

    def _allreduce(self, values, op):
        from jax.experimental import multihost_utils
        values = np.asarray(values, dtype=np.int64)
        # Ship the vector as raw bytes: JAX canonicalizes int64 arrays to
        # int32 when jax_enable_x64 is off (the default), which would
        # silently corrupt counts >= 2^31. uint8 survives canonicalization,
        # and the actual reduction happens on host at full precision.
        payload = values.tobytes()
        gathered = np.asarray(
            multihost_utils.process_allgather(
                np.frombuffer(payload, dtype=np.uint8)))
        per_rank = np.stack([
            np.frombuffer(row.tobytes(), dtype=np.int64)
            for row in gathered.reshape(self.world_size, -1)
        ])
        return op(per_rank, axis=0).astype(np.int64)

    def allreduce_sum(self, values):
        return self._allreduce(values, np.sum)

    def allreduce_max(self, values):
        return self._allreduce(values, np.max)


class ThreadGroupCommunicator(Communicator):
    """N SPMD ranks as threads with real barrier/allreduce semantics.

    Test harness for lockstep algorithms (balancer, censuses). Create the
    group with :meth:`spawn`, which runs ``fn(comm)`` on every rank-thread
    and re-raises the first failure.
    """

    class _Shared:

        def __init__(self, world_size):
            self.barrier = threading.Barrier(world_size)
            self.lock = threading.Lock()
            self.reduce_buf = None
            self.reduce_result = None

    def __init__(self, rank, world_size, shared):
        self._rank = rank
        self._world_size = world_size
        self._shared = shared

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    def barrier(self):
        self._shared.barrier.wait()

    def _allreduce(self, values, op):
        values = np.asarray(values, dtype=np.int64)
        with self._shared.lock:
            if self._shared.reduce_buf is None:
                self._shared.reduce_buf = []
            self._shared.reduce_buf.append(values)
        self._shared.barrier.wait()
        if self._rank == 0:
            self._shared.reduce_result = op(
                np.stack(self._shared.reduce_buf), axis=0).astype(np.int64)
            self._shared.reduce_buf = None
        self._shared.barrier.wait()
        # Copy: every rank must own its result so in-place mutation cannot
        # alias across rank-threads (matching JaxCommunicator semantics).
        result = self._shared.reduce_result.copy()
        self._shared.barrier.wait()
        return result

    def allreduce_sum(self, values):
        return self._allreduce(values, np.sum)

    def allreduce_max(self, values):
        return self._allreduce(values, np.max)

    @classmethod
    def spawn(cls, world_size, fn):
        """Run ``fn(comm)`` on ``world_size`` rank-threads; returns the list
        of per-rank return values; re-raises the first exception."""
        shared = cls._Shared(world_size)
        results = [None] * world_size
        errors = [None] * world_size

        def run(rank):
            try:
                results[rank] = fn(cls(rank, world_size, shared))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors[rank] = e
                # Break the barrier so peers don't deadlock.
                shared.barrier.abort()

        threads = [
            threading.Thread(target=run, args=(r,)) for r in range(world_size)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for e in errors:
            if e is not None and not isinstance(e, threading.BrokenBarrierError):
                raise e
        for e in errors:
            if e is not None:
                raise e
        return results


def get_communicator():
    """LocalCommunicator unless jax.distributed is up with >1 process."""
    try:
        import jax
    except ImportError:
        return LocalCommunicator()
    if jax.process_count() > 1:
        return JaxCommunicator()
    return LocalCommunicator()


def node_info():
    """(node_rank, num_nodes) of THIS host — real host identity, not a
    dp-group approximation. On TPU, one jax process == one host.
    Returns (0, 1) when jax is absent, single-process, or
    jax.distributed is not yet initialized — in that case NOTHING is
    queried, so calling this early never interferes with a later
    jax.distributed.initialize(). Once the distributed backend is up,
    the public jax.process_index()/process_count() accessors are used
    (they may touch the local XLA client, which is already inevitable at
    that point). (Replaces the reference's env-var walk,
    lddl/torch/utils.py:49-91.)"""
    try:
        import jax
        if not jax.distributed.is_initialized():
            return 0, 1
        # Public accessors are safe once is_initialized() is true (they
        # read, never initialize, the already-up backend). The private
        # global_state remains only as a fallback for jax versions whose
        # process_index() still force-initializes (ADVICE round 3).
        try:
            return int(jax.process_index()), int(jax.process_count())
        except Exception:
            from jax._src import distributed
            state = distributed.global_state
            return int(state.process_id), int(state.num_processes)
    except Exception:
        return 0, 1
