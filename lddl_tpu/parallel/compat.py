"""jax API compatibility: new-jax spellings on jax 0.4.x.

The models/mesh layer is written against the current jax surface
(``jax.set_mesh``, ``jax.shard_map``, ``jax.lax.pcast``); this container
pins jax 0.4.37, where those names live elsewhere or do not exist. One
shim module keeps every call site on the modern spelling and confines the
version probing here:

- ``set_mesh(mesh)``: ``jax.set_mesh`` context manager when present;
  otherwise the ``Mesh`` object itself (in 0.4.x ``with mesh:`` sets the
  thread-local physical mesh that flax's logical-axis machinery and bare
  PartitionSpecs resolve against — the same effect).
- ``shard_map(...)``: ``jax.shard_map`` when present; otherwise
  ``jax.experimental.shard_map.shard_map`` with the ``check_vma`` kwarg
  translated to its old name ``check_rep``.
- ``pcast(x, axes, to=...)``: ``jax.lax.pcast`` when present; otherwise
  identity — 0.4.x shard_map has no varying-axis tracking to satisfy, and
  every call site runs under ``check_vma=False`` anyway.
"""

import jax


def set_mesh(mesh):
    """Context manager making ``mesh`` the ambient mesh at trace time."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh  # 0.4.x: Mesh is itself the context manager


def shard_map(f, mesh=None, in_specs=None, out_specs=None, check_vma=None,
              **kwargs):
    """Modern ``jax.shard_map`` signature on either jax generation."""
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


def get_abstract_mesh():
    """The ambient mesh at trace time: ``jax.sharding.get_abstract_mesh``
    when present; on 0.4.x the thread-local physical mesh that ``with
    mesh:`` (our ``set_mesh``) installs — an empty ``Mesh()`` when none,
    matching the new API's empty abstract mesh."""
    import jax.sharding
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return jax.sharding.get_abstract_mesh()
    from jax.interpreters import pxla
    return pxla.thread_resources.env.physical_mesh


def pcast(x, axes, to=None):
    """``jax.lax.pcast`` where it exists; identity on 0.4.x (no varying-
    axis type system — only safe because call sites disable the checker
    via ``check_vma=False``)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to=to)
    return x
