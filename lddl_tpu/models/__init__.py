from .bert import BertConfig, BertForPreTraining
from .train import (
    TrainState,
    create_train_state,
    make_sharded_train_step,
    pretrain_loss,
)

__all__ = [
    "BertConfig",
    "BertForPreTraining",
    "TrainState",
    "create_train_state",
    "make_sharded_train_step",
    "pretrain_loss",
]
