from .bart import BartConfig, BartForPreTraining, bart_batch_loss
from .bert import (BertConfig, BertForPreTraining,
                   BertForPreTrainingPacked)
from .checkpoint import latest_step, restore_train_state, save_train_state
from .train import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_sharded_multi_step,
    make_sharded_train_step,
    pretrain_loss,
)

__all__ = [
    "BartConfig",
    "BartForPreTraining",
    "bart_batch_loss",
    "BertConfig",
    "BertForPreTraining",
    "BertForPreTrainingPacked",
    "latest_step",
    "restore_train_state",
    "save_train_state",
    "TrainState",
    "create_train_state",
    "make_eval_step",
    "make_sharded_multi_step",
    "make_sharded_train_step",
    "pretrain_loss",
]
