from .bart import BartConfig, BartForPreTraining, bart_batch_loss
from .bert import BertConfig, BertForPreTraining
from .train import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_sharded_train_step,
    pretrain_loss,
)

__all__ = [
    "BartConfig",
    "BartForPreTraining",
    "bart_batch_loss",
    "BertConfig",
    "BertForPreTraining",
    "TrainState",
    "create_train_state",
    "make_eval_step",
    "make_sharded_train_step",
    "pretrain_loss",
]
