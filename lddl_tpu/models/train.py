"""Sharded pretraining step: loss, optimizer, pjit wiring.

The full consumer of the loader contract: batches (from
lddl_tpu.loader.to_device_batch) -> jitted forward/backward on an arbitrary
mesh, with params/opt-state sharded by the model's logical axis rules and
the batch sharded over the data axes. All collectives are XLA-inserted
(psum for row-parallel matmuls and the data-parallel grad reduction,
all-gather around the sequence-sharded regions).
"""

import functools

import jax
import jax.numpy as jnp
import optax
import flax.linen as nn
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import compat
from .bert import BertForPreTraining, axis_rules_for


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: dict
    opt_state: optax.OptState
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt_state = self.tx.update(grads, self.opt_state,
                                                self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt_state,
        )


def pretrain_loss(mlm_logits, nsp_logits, labels, next_sentence_labels,
                  ignore_index=-1):
    """Masked-LM cross entropy (mean over masked positions) + NSP cross
    entropy. NSP labels may be [B] (one sample per row) or, for packed
    rows, [B, P] with ``ignore_index`` padding unused pack slots — the
    mean then runs over real samples only. Returns (loss, metrics)."""
    mask = labels != ignore_index
    safe_labels = jnp.where(mask, labels, 0)
    mlm_ll = optax.softmax_cross_entropy_with_integer_labels(
        mlm_logits, safe_labels)
    denom = jnp.maximum(mask.sum(), 1)
    mlm_loss = jnp.where(mask, mlm_ll, 0.0).sum() / denom
    nsp_mask = next_sentence_labels != ignore_index
    nsp_safe = jnp.where(nsp_mask, next_sentence_labels, 0)
    nsp_ll = optax.softmax_cross_entropy_with_integer_labels(
        nsp_logits, nsp_safe)
    nsp_denom = jnp.maximum(nsp_mask.sum(), 1)
    nsp_loss = jnp.where(nsp_mask, nsp_ll, 0.0).sum() / nsp_denom
    loss = mlm_loss + nsp_loss
    mlm_correct = jnp.where(
        mask, jnp.argmax(mlm_logits, axis=-1) == safe_labels, False)
    nsp_correct = jnp.where(
        nsp_mask, jnp.argmax(nsp_logits, -1) == nsp_safe, False)
    metrics = {
        "loss": loss,
        "mlm_loss": mlm_loss,
        "nsp_loss": nsp_loss,
        "mlm_accuracy": mlm_correct.sum() / denom,
        "nsp_accuracy": nsp_correct.sum() / nsp_denom,
    }
    return loss, metrics


def make_optimizer(learning_rate=1e-4, weight_decay=0.01, warmup_steps=100,
                   total_steps=10000, b1=0.9, b2=0.999, clip_norm=1.0,
                   mu_dtype=None):
    """AdamW with warmup-cosine schedule and global-norm clipping.

    ``mu_dtype`` (e.g. ``jnp.bfloat16``) stores the first adam moment in
    a reduced dtype. This is a MEMORY option, not a speed option: it
    halves mu's bytes at rest, but the on-chip A/B (STEP_PROFILE.json
    ``mu_bf16_ab_step_ms``) measured it ~1.3 ms/step SLOWER on bert_large
    — XLA's convert ops cost more than the HBM traffic they save. Default
    None keeps fp32: identical update numerics to rounds 1-4 and the
    faster step (the variance nu always stays fp32)."""
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def _batch_inputs(model, batch):
    """Positional model inputs drawn from the batch dict. Models declare
    their consumed keys via BATCH_INPUTS (BERT's triple by default)."""
    keys = getattr(model, "BATCH_INPUTS",
                   ("input_ids", "token_type_ids", "attention_mask"))
    return tuple(batch[k] for k in keys)


def _init_variables(model, rng, sample_batch):
    return model.init({"params": rng}, *_batch_inputs(model, sample_batch),
                      deterministic=True)


def param_shardings_of(mesh, model, sample_batch, abstract_variables=None):
    """NamedShardings for the (unboxed) param pytree, derived from the
    model's logical axis annotations + the mesh-filtered axis rules."""
    if abstract_variables is None:
        abstract_variables = jax.eval_shape(
            lambda rng: _init_variables(model, rng, sample_batch),
            jax.random.PRNGKey(0))
    logical_specs = nn.get_partition_spec(abstract_variables)["params"]
    return nn.logical_to_mesh_sharding(logical_specs, mesh,
                                       axis_rules_for(mesh))


def _mirror_param_shardings(opt_state, param_treedef, param_shardings,
                            replicated):
    """Opt-state subtrees structured like the param tree (adam mu/nu) get
    the param shardings; everything else replicates."""
    def matches(node):
        try:
            return jax.tree.structure(node) == param_treedef
        except Exception:
            return False

    if matches(opt_state):
        return param_shardings
    if hasattr(opt_state, "_fields"):  # namedtuple optax state
        return type(opt_state)(*[
            _mirror_param_shardings(getattr(opt_state, f), param_treedef,
                                    param_shardings, replicated)
            for f in opt_state._fields
        ])
    if isinstance(opt_state, (tuple, list)):
        return type(opt_state)(
            _mirror_param_shardings(s, param_treedef, param_shardings,
                                    replicated) for s in opt_state)
    return jax.tree.map(lambda _: replicated, opt_state)


def create_train_state(config, mesh, sample_batch, seed=0, optimizer=None,
                       model=None):
    """Initialize a sharded TrainState on ``mesh``.

    Params materialize directly as shards (init runs under jit with the
    target shardings), so models bigger than one device's memory
    initialize fine. Returns (state, state_shardings).
    """
    model = model or BertForPreTraining(config)
    tx = optimizer or make_optimizer()

    def init_fn(rng):
        variables = _init_variables(model, rng, sample_batch)
        params = nn.meta.unbox(variables)["params"]
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            tx=tx,
        )

    # One abstract trace serves both the param shardings and the opt-state
    # structure (tracing a large model twice costs seconds of startup).
    abstract_vars = jax.eval_shape(
        lambda rng: _init_variables(model, rng, sample_batch),
        jax.random.PRNGKey(seed))
    param_shardings = param_shardings_of(mesh, model, sample_batch,
                                         abstract_variables=abstract_vars)
    abstract_params = nn.meta.unbox(abstract_vars)["params"]
    abstract_opt = jax.eval_shape(tx.init, abstract_params)
    replicated = NamedSharding(mesh, P())
    shardings = TrainState(
        step=replicated,
        params=param_shardings,
        opt_state=_mirror_param_shardings(
            abstract_opt, jax.tree.structure(abstract_params),
            param_shardings, replicated),
        tx=tx,
    )
    with compat.set_mesh(mesh), nn.logical_axis_rules(
            axis_rules_for(mesh)):
        state = jax.jit(init_fn, out_shardings=shardings)(
            jax.random.PRNGKey(seed))
    return state, shardings


def bert_batch_loss(outputs, batch, ignore_index=-1):
    """Default loss adapter: BertForPreTraining outputs -> pretrain_loss."""
    mlm_logits, nsp_logits = outputs
    return pretrain_loss(mlm_logits, nsp_logits, batch["labels"],
                         batch["next_sentence_labels"],
                         ignore_index=ignore_index)


def _resolve_batch_loss(batch_loss, ignore_index):
    if batch_loss is not None and ignore_index != -1:
        raise ValueError(
            "ignore_index only configures the default BERT loss; bind it "
            "into your batch_loss instead")
    return batch_loss or functools.partial(bert_batch_loss,
                                           ignore_index=ignore_index)


def mlm_gather_cap(seq_len, n_samples_per_row=1):
    """Static cap P on masked positions per row for the gathered MLM head:
    the masking budget (15%) plus a 4-sigma binomial margin (dynamic
    masking draws ~Binomial(L, 0.15) per sample, uncapped), rounded up to
    a multiple of 8 for layout friendliness. Rows that exceed P (p < 1e-4
    at 4 sigma) drop the excess labels — counted in the step metrics as
    ``mlm_dropped_labels``, never silent."""
    import math
    l_eff = seq_len / max(n_samples_per_row, 1)
    per_sample = 0.15 * l_eff + 1.43 * math.sqrt(l_eff)
    p = int(math.ceil(per_sample)) * max(n_samples_per_row, 1)
    return min(seq_len, -(-p // 8) * 8)


def _dropout_key(model, seed):
    """Per-step dropout base key honoring cfg.dropout_rng_impl. "threefry"
    means jax's default threefry2x32 (via PRNGKey, so the name in the
    config stays version-stable); anything else is passed to
    jax.random.key(impl=...) verbatim (e.g. "rbg")."""
    impl = getattr(getattr(model, "cfg", None), "dropout_rng_impl", None)
    if impl is None or impl == "threefry":
        return jax.random.PRNGKey(seed)
    return jax.random.key(seed, impl=impl)


def _mlm_gather_prologue(model, batch, ignore_index, enabled):
    """Shared train/eval gather step: returns (model_kwargs, batch,
    extra_metrics) — with the gathered MLM head engaged, batch["labels"]
    is replaced by the gathered [B, P] labels and the dropped-label count
    is reported. A no-op (({}, batch, {})) when disabled or not
    applicable."""
    gather = _mlm_gather_of(model, batch, ignore_index) if enabled else None
    if gather is None:
        return {}, batch, {}
    pos, gathered_labels, dropped = gather
    return ({"masked_positions": pos}, dict(batch, labels=gathered_labels),
            {"mlm_dropped_labels": dropped})


def _mlm_gather_of(model, batch, ignore_index=-1):
    """(masked_positions [B,P], gathered labels [B,P], dropped count) when
    the model opts into the gathered MLM head, else None. Positions are
    the first P masked columns per row (ascending; rows with fewer than P
    pad with unmasked columns whose labels are already ignore_index)."""
    cfg = getattr(model, "cfg", None)
    if not getattr(cfg, "mlm_gather", False) or "labels" not in batch:
        return None
    labels = batch["labels"]
    seq_len = labels.shape[-1]
    n_per_row = 1
    if "cls_positions" in batch:  # packed rows: several samples per row
        n_per_row = batch["cls_positions"].shape[-1]
    p = mlm_gather_cap(seq_len, n_per_row)
    if p >= seq_len:
        return None  # gather would not shrink anything
    mask = labels != ignore_index
    # Strictly-decreasing positive scores at masked columns, 0 elsewhere:
    # top_k then yields the first P masked positions in ascending order.
    score = jnp.where(mask, seq_len - jnp.arange(seq_len)[None, :], 0)
    _, pos = jax.lax.top_k(score, p)
    gathered = jnp.take_along_axis(labels, pos, axis=1)
    dropped = mask.sum() - (gathered != ignore_index).sum()
    return pos, gathered, dropped


def _make_step_fn(model, batch_loss, ignore_index=-1, mlm_gather_ok=True):
    """The un-jitted SPMD step body shared by the single- and multi-step
    entry points: (state, batch, seed) -> (state, metrics).

    ``mlm_gather_ok=False`` disables the gathered MLM head: the gather
    rewrites batch["labels"] under the DEFAULT BERT loss's conventions
    (labels are [B, L] MLM ids, ignore_index marks unmasked), so a
    custom batch_loss with its own label semantics must see the original
    batch and full-sequence logits."""

    def step_fn(state, batch, seed):
        dropout_rng = jax.random.fold_in(_dropout_key(model, seed),
                                         state.step)
        kwargs, batch, extra = _mlm_gather_prologue(
            model, batch, ignore_index, mlm_gather_ok)

        def loss_fn(params):
            outputs = model.apply(
                {"params": params},
                *_batch_inputs(model, batch),
                deterministic=False,
                rngs={"dropout": dropout_rng},
                **kwargs,
            )
            return batch_loss(outputs, batch)

        (_, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        if extra:
            metrics = dict(metrics, **extra)
        new_state = state.apply_gradients(grads)
        return new_state, metrics

    return step_fn


def make_sharded_train_step(mesh, config, model=None, ignore_index=-1,
                            donate=True, batch_loss=None):
    """A jitted SPMD train step: (state, batch, seed) -> (state, metrics).

    Batch arrays must be globally-sharded jax.Arrays over the mesh's data
    axes (use lddl_tpu.loader.to_device_batch). Dropout randomness is
    deterministic per (seed, step). ``batch_loss(outputs, batch)`` ->
    (loss, metrics) adapts non-BERT models (e.g. models.bart; bind its
    ignore_index yourself, e.g. functools.partial(bart_batch_loss,
    ignore_index=...))."""
    model = model or BertForPreTraining(config)
    step_fn = _make_step_fn(model,
                            _resolve_batch_loss(batch_loss, ignore_index),
                            ignore_index, mlm_gather_ok=batch_loss is None)

    jitted = jax.jit(step_fn, donate_argnums=(0,) if donate else ())

    def wrapped(state, batch, seed=0):
        # Both contexts must be live at trace time: axis_rules resolves the
        # logical constraints, use_mesh resolves bare PartitionSpecs.
        with compat.set_mesh(mesh), nn.logical_axis_rules(
                axis_rules_for(mesh)):
            return jitted(state, batch, seed)

    return wrapped


def make_sharded_multi_step(mesh, config, n_steps, model=None,
                            ignore_index=-1, donate=True, batch_loss=None):
    """``n_steps`` train steps in ONE dispatch: ``lax.scan`` over the step
    body — (state, batches, seed) -> (state, stacked metrics).

    The idiomatic TPU training-loop shape: one XLA computation covers many
    optimizer steps, so per-dispatch host latency (python, runtime RPC —
    ~100 ms/dispatch on a tunneled chip) is paid once per ``n_steps``
    instead of per step, and the compiler can overlap step boundaries.

    ``batches`` leaves carry a leading ``[n_steps, ...]`` axis; each scan
    iteration consumes one slice (use lddl_tpu.loader.to_device_step_batches,
    or stack one batch n_steps times to re-feed it). Dropout still varies
    per step: the seed is folded with ``state.step``, which increments
    inside the scan."""
    model = model or BertForPreTraining(config)
    step_fn = _make_step_fn(model,
                            _resolve_batch_loss(batch_loss, ignore_index),
                            ignore_index, mlm_gather_ok=batch_loss is None)

    def multi_step_fn(state, batches, seed):
        def body(state, batch):
            return step_fn(state, batch, seed)

        return jax.lax.scan(body, state, batches, length=n_steps)

    jitted = jax.jit(multi_step_fn, donate_argnums=(0,) if donate else ())

    def wrapped(state, batches, seed=0):
        with compat.set_mesh(mesh), nn.logical_axis_rules(
                axis_rules_for(mesh)):
            return jitted(state, batches, seed)

    return wrapped


def make_eval_step(mesh, config, model=None, ignore_index=-1,
                   batch_loss=None):
    """Jitted forward-only step returning metrics."""
    model = model or BertForPreTraining(config)
    if batch_loss is not None and ignore_index != -1:
        raise ValueError(
            "ignore_index only configures the default BERT loss; bind it "
            "into your batch_loss instead")
    mlm_gather_ok = batch_loss is None  # default-loss conventions only
    batch_loss = batch_loss or functools.partial(bert_batch_loss,
                                                 ignore_index=ignore_index)

    def step_fn(params, batch):
        kwargs, batch, extra = _mlm_gather_prologue(
            model, batch, ignore_index, mlm_gather_ok)
        outputs = model.apply(
            {"params": params},
            *_batch_inputs(model, batch),
            deterministic=True,
            **kwargs,
        )
        _, metrics = batch_loss(outputs, batch)
        if extra:
            metrics = dict(metrics, **extra)
        return metrics

    jitted = jax.jit(step_fn)
    warned = [False]

    def wrapped(params, batch):
        with compat.set_mesh(mesh), nn.logical_axis_rules(
                axis_rules_for(mesh)):
            metrics = jitted(params, batch)
        # Train steps meter mlm_dropped_labels and tolerate the 4-sigma
        # cap; EVAL numbers are quoted as exact, so a capped row must be
        # loud (ADVICE r4). The host read costs one tiny-scalar sync per
        # eval step — eval callers read the metrics anyway.
        if not warned[0] and "mlm_dropped_labels" in metrics:
            if int(metrics["mlm_dropped_labels"]) > 0:
                warned[0] = True
                import warnings
                warnings.warn(
                    "mlm_gather dropped masked labels in an eval step: the "
                    "reported loss excludes them. Labels exceeded the "
                    "4-sigma cap (mlm_gather_cap); evaluate with "
                    "config.mlm_gather=False for exact loss.",
                    RuntimeWarning, stacklevel=2)
        return metrics

    return wrapped
