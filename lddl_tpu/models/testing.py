"""Synthetic batches matching the loader's BERT pretraining contract.

One definition shared by tests, the driver compile-check entry, and the
multichip dryrun, so contract changes (new keys, dtypes) propagate
everywhere at once.
"""

import numpy as np


def fake_pretrain_batch(vocab_size, batch, seq_len, seed=0,
                        segment_split=False):
    rng = np.random.default_rng(seed)
    ids = rng.integers(5, vocab_size, (batch, seq_len)).astype(np.int32)
    segment = np.zeros((batch, seq_len), np.int32)
    if segment_split:
        segment[:, seq_len // 2:] = 1
    return {
        "input_ids": ids,
        "token_type_ids": segment,
        "attention_mask": np.ones((batch, seq_len), np.int32),
        "labels": np.where(rng.random((batch, seq_len)) < 0.15, ids,
                           -1).astype(np.int32),
        "next_sentence_labels": rng.integers(0, 2, (batch,)).astype(np.int32),
    }


def fake_packed_pretrain_batch(vocab_size, rows, seq_len, max_per_row,
                               seed=0):
    """Synthetic batch matching the PACKED loader contract
    (loader/bert.BertPackedCollate / BertPrepackedCollate output): two
    samples per row (one when ``max_per_row`` is 1), block-diagonal
    segments, per-slot NSP labels padded with -1 — param-init shape/key
    fodder for BertForPreTrainingPacked."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(5, vocab_size, (rows, seq_len)).astype(np.int32)
    n_samples = min(2, max_per_row)
    half = seq_len // 2 if n_samples == 2 else seq_len
    segments = np.ones((rows, seq_len), np.int32)
    segments[:, half:] = n_samples
    position_ids = np.concatenate(
        [np.arange(half), np.arange(seq_len - half)]).astype(np.int32)
    position_ids = np.broadcast_to(position_ids, (rows, seq_len)).copy()
    cls_positions = np.zeros((rows, max_per_row), np.int32)
    if n_samples == 2:
        cls_positions[:, 1] = half
    nsp = np.full((rows, max_per_row), -1, np.int32)
    nsp[:, :n_samples] = rng.integers(0, 2,
                                      (rows, n_samples)).astype(np.int32)
    return {
        "input_ids": ids,
        "token_type_ids": np.zeros((rows, seq_len), np.int32),
        "attention_mask": np.ones((rows, seq_len), np.int32),
        "segments": segments,
        "position_ids": position_ids,
        "cls_positions": cls_positions,
        "next_sentence_labels": nsp,
        "labels": np.where(rng.random((rows, seq_len)) < 0.15, ids,
                           -1).astype(np.int32),
    }


def fake_bart_batch(vocab_size, batch, seq_len, seed=0):
    """Synthetic batch matching the BART loader contract
    (loader/bart.py: input_ids/attention_mask/decoder_input_ids/labels)."""
    rng = np.random.default_rng(seed)
    dec = rng.integers(5, vocab_size, (batch, seq_len)).astype(np.int32)
    labels = np.roll(dec, -1, axis=1).astype(np.int32)
    labels[:, -1] = -1
    return {
        "input_ids": rng.integers(5, vocab_size,
                                  (batch, seq_len)).astype(np.int32),
        "attention_mask": np.ones((batch, seq_len), np.int32),
        "decoder_input_ids": dec,
        "labels": labels,
    }
