"""Synthetic batches matching the loader's BERT pretraining contract.

One definition shared by tests, the driver compile-check entry, and the
multichip dryrun, so contract changes (new keys, dtypes) propagate
everywhere at once.
"""

import numpy as np


def fake_pretrain_batch(vocab_size, batch, seq_len, seed=0,
                        segment_split=False):
    rng = np.random.default_rng(seed)
    ids = rng.integers(5, vocab_size, (batch, seq_len)).astype(np.int32)
    segment = np.zeros((batch, seq_len), np.int32)
    if segment_split:
        segment[:, seq_len // 2:] = 1
    return {
        "input_ids": ids,
        "token_type_ids": segment,
        "attention_mask": np.ones((batch, seq_len), np.int32),
        "labels": np.where(rng.random((batch, seq_len)) < 0.15, ids,
                           -1).astype(np.int32),
        "next_sentence_labels": rng.integers(0, 2, (batch,)).astype(np.int32),
    }


def fake_bart_batch(vocab_size, batch, seq_len, seed=0):
    """Synthetic batch matching the BART loader contract
    (loader/bart.py: input_ids/attention_mask/decoder_input_ids/labels)."""
    rng = np.random.default_rng(seed)
    dec = rng.integers(5, vocab_size, (batch, seq_len)).astype(np.int32)
    labels = np.roll(dec, -1, axis=1).astype(np.int32)
    labels[:, -1] = -1
    return {
        "input_ids": rng.integers(5, vocab_size,
                                  (batch, seq_len)).astype(np.int32),
        "attention_mask": np.ones((batch, seq_len), np.int32),
        "decoder_input_ids": dec,
        "labels": labels,
    }
