"""BART-style encoder-decoder for denoising pretraining, TPU-first.

The reference preprocesses BART chunks but ships neither a BART loader
nor any model (SURVEY.md §2.3/§2.5); lddl_tpu completes the path:
loader/bart.py emits ``{input_ids, attention_mask, decoder_input_ids,
labels}`` batches, and this model consumes them — so the BART contract
is exercised by a real jitted encoder-decoder forward/backward on a
device mesh, exactly as models/bert.py does for the BERT contract.

Sharding follows the same logical-axis scheme as models/bert.py
(LOGICAL_AXIS_RULES): Megatron-style column/row-parallel projections
over tp, batch over dp/fsdp, activations sequence-sharded over sp
between blocks with gathers around attention. bf16 activations, fp32
params. Decoder self-attention is causal; cross-attention keys off the
encoder output.
"""

import dataclasses
from typing import Any

import jax.numpy as jnp
import flax.linen as nn

from .bert import (  # noqa: F401 (shared rules)
    LOGICAL_AXIS_RULES,
    _attention,
    _feed_forward,
    axis_rules_for,
    with_logical,
)


@dataclasses.dataclass(frozen=True)
class BartConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    dtype: Any = jnp.bfloat16
    # "auto"/"flash"/"ring" engage blockwise attention for the ENCODER's
    # bidirectional self-attention only (models/attention.py); the
    # decoder's causal self-attention and the cross-attention stay dense.
    # See BertConfig.attention_impl for the auto selection rule.
    attention_impl: str = "auto"
    # Rematerialize encoder/decoder layers on backward (jax.checkpoint):
    # ~1/3 more FLOPs for O(num_layers) less activation memory.
    remat: bool = False
    # Dropout PRNG implementation; see BertConfig.dropout_rng_impl.
    dropout_rng_impl: str = "rbg"

    def __post_init__(self):
        if self.attention_impl not in ("auto", "dense", "ring", "flash"):
            raise ValueError("attention_impl must be auto|dense|ring|flash")
        if self.dropout_rng_impl not in ("rbg", "threefry"):
            raise ValueError("dropout_rng_impl must be rbg|threefry")

    @staticmethod
    def bart_base(**kw):
        return BartConfig(**kw)

    @staticmethod
    def tiny(**kw):
        """For tests and dryruns."""
        kw.setdefault("vocab_size", 512)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_encoder_layers", 2)
        kw.setdefault("num_decoder_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("max_position_embeddings", 128)
        return BartConfig(**kw)


def _dense_init(cfg):
    return nn.initializers.normal(stddev=cfg.initializer_range)


class Embeddings(nn.Module):
    """Shared token embedding + learned positions (one instance each for
    encoder and decoder inputs; the token table is shared via the parent
    passing the same module)."""

    cfg: BartConfig

    @nn.compact
    def __call__(self, token_embed, input_ids, deterministic):
        cfg = self.cfg
        x = token_embed(input_ids)
        x = with_logical(x, ("batch", "seq", None))
        pos = nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size, dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed_vocab", None)),
            name="positions")(jnp.arange(input_ids.shape[1])[None, :])
        x = with_logical(x + pos, ("batch", "seq", "act_embed"))
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="layer_norm")(x)
        return nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)


def causal_bias(length):
    """[1, 1, L, L] additive causal mask (finite -1e9, see
    models/attention.py)."""
    tri = jnp.tril(jnp.ones((length, length), jnp.bool_))
    return jnp.where(tri, 0.0, -1e9)[None, None, :, :]


class EncoderLayer(nn.Module):
    cfg: BartConfig

    @nn.compact
    def __call__(self, x, padding_mask, deterministic):
        cfg = self.cfg
        a = _attention(cfg, "self_attention")(x, x, padding_mask,
                                              deterministic)
        a = nn.Dropout(cfg.hidden_dropout)(a, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="self_norm")(x + a)
        h = _feed_forward(cfg)(x)
        h = nn.Dropout(cfg.hidden_dropout)(h, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ffn_norm")(x + h)
        return with_logical(x, ("batch", "seq", "act_embed"))


class DecoderLayer(nn.Module):
    cfg: BartConfig

    @nn.compact
    def __call__(self, x, enc, self_bias, enc_padding_mask, deterministic):
        cfg = self.cfg
        # Causal self-attention (extra_bias forces the dense path; ring is
        # bidirectional-only).
        a = _attention(cfg, "self_attention")(x, x, None, deterministic,
                                              extra_bias=self_bias)
        a = nn.Dropout(cfg.hidden_dropout)(a, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="self_norm")(x + a)
        c = _attention(cfg, "cross_attention")(x, enc, enc_padding_mask,
                                               deterministic)
        c = nn.Dropout(cfg.hidden_dropout)(c, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="cross_norm")(x + c)
        h = _feed_forward(cfg)(x)
        h = nn.Dropout(cfg.hidden_dropout)(h, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ffn_norm")(x + h)
        return with_logical(x, ("batch", "seq", "act_embed"))


class BartForPreTraining(nn.Module):
    """Encoder-decoder + LM head over the decoder states.

    Consumes the loader/bart.py batch contract positionally (see
    BATCH_INPUTS); returns fp32 logits [B, L_dec, vocab].
    """

    cfg: BartConfig
    BATCH_INPUTS = ("input_ids", "attention_mask", "decoder_input_ids")

    @nn.compact
    def __call__(self, input_ids, attention_mask, decoder_input_ids,
                 deterministic=True):
        cfg = self.cfg
        # Rows on fsdp, embed dim replicated — same rationale as the BERT
        # Embeddings tables (gather outputs must come out (batch, seq)-
        # sharded, not embed-sharded; see bert.LOGICAL_AXIS_RULES).
        token_embed = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed_vocab", None)),
            name="shared_embeddings")

        enc_cls = (nn.remat(EncoderLayer, static_argnums=(3,))
                   if cfg.remat else EncoderLayer)
        dec_cls = (nn.remat(DecoderLayer, static_argnums=(5,))
                   if cfg.remat else DecoderLayer)
        x = Embeddings(cfg, name="encoder_embed")(
            token_embed, input_ids, deterministic)
        for i in range(cfg.num_encoder_layers):
            x = enc_cls(cfg, name="encoder_{}".format(i))(
                x, attention_mask, deterministic)
        enc = x

        self_bias = causal_bias(decoder_input_ids.shape[1])
        y = Embeddings(cfg, name="decoder_embed")(
            token_embed, decoder_input_ids, deterministic)
        for i in range(cfg.num_decoder_layers):
            y = dec_cls(cfg, name="decoder_{}".format(i))(
                y, enc, self_bias, attention_mask, deterministic)

        logits = nn.Dense(
            cfg.vocab_size, dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed", "vocab")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("vocab",)),
            name="lm_head")(y)
        return logits


def bart_batch_loss(logits, batch, ignore_index=-1):
    """Denoising CE over the clean labels (ignore_index on padding) ->
    (loss, metrics). The batch_loss adapter for models.train."""
    import optax

    labels = batch["labels"]
    mask = labels != ignore_index
    safe = jnp.where(mask, labels, 0)
    ll = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
    denom = jnp.maximum(mask.sum(), 1)
    loss = jnp.where(mask, ll, 0.0).sum() / denom
    correct = jnp.where(mask, jnp.argmax(logits, -1) == safe, False)
    return loss, {
        "loss": loss,
        "accuracy": correct.sum() / denom,
    }
