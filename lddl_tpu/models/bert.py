"""BERT for pretraining (MLM + NSP), TPU-first.

The reference ships no models — its "training" is a mock loop
(benchmarks/torch_train.py) that only consumes batches. lddl_tpu includes a
real reference consumer so the loader's contract (shapes, masking, binning)
is exercised by an actual jitted forward/backward on a device mesh, and so
benchmarks can measure end-to-end step time rather than loader time alone.

TPU design notes:
- bf16 activations, fp32 params/optimizer — MXU-native without loss-scale
  bookkeeping.
- Megatron-style tensor parallelism via flax logical axis names:
  QKV/MLP-in are column-parallel ("mlp"/"heads" -> tp), attention-out and
  MLP-out are row-parallel. XLA inserts the psums.
- Sequence parallelism: activations carry a "seq" logical axis; with the
  seq->sp rule, layernorm/embedding/dropout regions run sequence-sharded
  and XLA all-gathers only around attention (the Megatron-SP pattern),
  riding ICI.
- Everything static-shape; the loader's per-bin fixed lengths bound the
  compilation count.
"""

import dataclasses
from typing import Any

import jax.numpy as jnp
import flax.linen as nn

# Logical-to-mesh sharding rules (see lddl_tpu.parallel.mesh for axes).
#
# "embed" names PARAM embed dims and maps to fsdp: with an fsdp mesh axis
# the weights and optimizer state live fully sharded (ZeRO-style) and XLA
# all-gathers each weight just-in-time for its matmul. Activations use
# the separate "act_embed" name because their batch dim already rides
# fsdp — one array cannot use the axis twice.
LOGICAL_AXIS_RULES = (
    ("batch", ("dp", "fsdp")),
    ("seq", "sp"),
    ("embed", "fsdp"),
    ("act_embed", None),
    ("embed_out", None),
    ("mlp", "tp"),
    ("heads", "tp"),
    ("kv", None),
    ("vocab", "tp"),
    # Embedding-table ROWS (the token-id dim of nn.Embed tables) shard
    # over fsdp only — "vocab"(tp) there would make every step all-gather
    # the table across tp AND leave the gather output embed-sharded, which
    # XLA can only reshard to (batch, seq) via involuntary full
    # rematerialization (MULTICHIP_r04 warnings). With rows on fsdp the
    # table joins the normal ZeRO just-in-time param gather and the token
    # gather partitions cleanly over the (batch, seq)-sharded indices.
    ("embed_vocab", "fsdp"),
)

with_logical = nn.with_logical_constraint


def axis_rules_for(mesh):
    """LOGICAL_AXIS_RULES restricted to the axes ``mesh`` actually has, so
    one model definition runs on any mesh (dp-only, dp×tp, dp×tp×sp, ...).
    """
    rules = []
    for logical, target in LOGICAL_AXIS_RULES:
        if isinstance(target, tuple):
            present = tuple(a for a in target if a in mesh.axis_names)
            rules.append((logical, present if present else None))
        elif target is not None and target not in mesh.axis_names:
            rules.append((logical, None))
        else:
            rules.append((logical, target))
    return tuple(rules)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: Any = jnp.bfloat16  # activations; params stay fp32
    # "auto" (default): dense at the shortest bins, the pallas flash
    # kernel where it measurably wins or ties (L >= 256 since the
    # round-5 single-block kernels) AND computes identical math
    # (attention_dropout == 0 — flash skips prob dropout); the choice is
    # per traced sequence length, so no config silently runs the slower
    # impl (MODEL_BENCH.json). "dense": all-gather from sp into
    # full-sequence attention (Megatron-SP). "flash": always the pallas
    # kernel. "ring": sequence-parallel exact attention — K/V blocks
    # rotate over the sp ring (ops/ring_attention.py), no device ever
    # holds the full sequence; attention-prob dropout is skipped under
    # ring (standard for blockwise kernels). Falls back to dense when
    # the mesh has no sp axis (or sp == 1).
    attention_impl: str = "auto"
    # Rematerialize each encoder layer on the backward pass
    # (jax.checkpoint): activations are recomputed instead of stored,
    # trading ~1/3 more FLOPs for O(num_layers) less activation memory —
    # the standard lever for long sequences / big batches on HBM.
    remat: bool = False
    # Run the MLM head only at the masked positions: the train step
    # gathers the ~15% masked columns (a static cap P, see
    # train.mlm_gather_cap) before the vocab projection, cutting the
    # head's matmul FLOPs and its [B, L, vocab] fp32 logits (the largest
    # tensor of the step, and pure overhead at the ~85% unmasked
    # positions — loss and gradients are IDENTICAL, since unmasked logits
    # never contribute). Direct model.apply calls without
    # masked_positions still produce full [B, L, vocab] logits.
    mlm_gather: bool = True
    # PRNG implementation for the per-step dropout key. "rbg" drives the
    # TPU's hardware RNG through XLA's RngBitGenerator — measured 14.3 ms
    # (15%) off a bert_large L=512 train step vs threefry, which computes
    # the hash chain on the VPU (STEP_PROFILE.json). Dropout masks remain
    # deterministic in (seed, step) for a fixed program, but rbg draws are
    # not guaranteed bit-stable across compiler versions or mesh shapes —
    # set "threefry" if dropout masks must replay exactly everywhere.
    dropout_rng_impl: str = "rbg"

    def __post_init__(self):
        if self.attention_impl not in ("auto", "dense", "ring", "flash"):
            raise ValueError("attention_impl must be auto|dense|ring|flash")
        if self.dropout_rng_impl not in ("rbg", "threefry"):
            raise ValueError("dropout_rng_impl must be rbg|threefry")
        if self.attention_impl in ("ring", "flash") \
                and self.attention_dropout > 0:
            import warnings
            warnings.warn(
                "attention_impl='{}' skips attention-probability dropout "
                "(standard for blockwise kernels): with attention_dropout="
                "{} it trains a slightly different model than 'dense'. "
                "Set attention_dropout=0.0 to silence this.".format(
                    self.attention_impl, self.attention_dropout),
                stacklevel=2)

    @staticmethod
    def bert_base(**kw):
        return BertConfig(**kw)

    @staticmethod
    def bert_large(**kw):
        kw.setdefault("hidden_size", 1024)
        kw.setdefault("num_layers", 24)
        kw.setdefault("num_heads", 16)
        kw.setdefault("intermediate_size", 4096)
        return BertConfig(**kw)

    @staticmethod
    def tiny(**kw):
        """For tests and dryruns."""
        kw.setdefault("vocab_size", 512)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("max_position_embeddings", 128)
        return BertConfig(**kw)


def _dense_init(cfg):
    return nn.initializers.normal(stddev=cfg.initializer_range)


class Embeddings(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, deterministic,
                 position_ids=None):
        cfg = self.cfg
        # Embedding tables shard on their ROW (token-id) dim over fsdp
        # only — an embed-dim ("embed"→fsdp) sharding here would propagate
        # into the gather outputs as embed-sharded [B, L, E] activations
        # that XLA cannot reshard to (batch, seq) without involuntary full
        # rematerialization (see LOGICAL_AXIS_RULES).
        word = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed_vocab", None)),
            name="word_embeddings")(input_ids)
        word = with_logical(word, ("batch", "seq", None))
        if position_ids is None:
            position_ids = jnp.arange(input_ids.shape[1])[None, :]
        pos = nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size, dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed_vocab", None)),
            name="position_embeddings")(position_ids)
        typ = nn.Embed(
            cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                _dense_init(cfg), (None, None)),
            name="token_type_embeddings")(token_type_ids)
        x = word + pos + typ
        x = with_logical(x, ("batch", "seq", "act_embed"))
        x = nn.LayerNorm(epsilon=self.cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="layer_norm")(x)
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)
        return x


def _attention(cfg, name):
    """The shared MultiHeadAttention configured from a model config; child
    params named query/key/value/output (stable checkpoint trees)."""
    from .attention import MultiHeadAttention
    return MultiHeadAttention(
        hidden_size=cfg.hidden_size,
        num_heads=cfg.num_heads,
        dtype=cfg.dtype,
        dropout=cfg.attention_dropout,
        initializer_range=cfg.initializer_range,
        attention_impl=cfg.attention_impl,
        name=name)


def _feed_forward(cfg, name="ffn"):
    """The shared transformer MLP configured from a model config."""
    from .attention import FeedForward
    return FeedForward(
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        dtype=cfg.dtype,
        initializer_range=cfg.initializer_range,
        name=name)


class EncoderLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, deterministic, segments=None):
        cfg = self.cfg
        attn = _attention(cfg, "attention")(x, x, attention_mask,
                                            deterministic,
                                            segments=segments)
        attn = nn.Dropout(cfg.hidden_dropout)(attn, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="attention_norm")(x + attn)

        h = _feed_forward(cfg)(x)
        h = nn.Dropout(cfg.hidden_dropout)(h, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ffn_norm")(x + h)
        return with_logical(x, ("batch", "seq", "act_embed"))


class BertForPreTraining(nn.Module):
    """Encoder + MLM head + NSP head.

    Unpacked: returns (mlm_logits [B,L,vocab], nsp_logits [B,2]) in fp32.

    Packed rows (sequence packing, ops/packing.py): pass ``segments``
    [B,L] (per-token pack slot id, 0 = pad — attention becomes
    block-diagonal), ``position_ids`` [B,L] (restart at each packed
    sample, so every sample sees the same positions it would unpacked)
    and ``cls_positions`` [B,P] (each packed sample's [CLS] column);
    nsp_logits is then [B,P,2]. Params are identical either way — the
    same checkpoint serves packed and unpacked training.
    """
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, attention_mask,
                 segments=None, position_ids=None, cls_positions=None,
                 deterministic=True, masked_positions=None):
        cfg = self.cfg
        x = Embeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, deterministic,
            position_ids=position_ids)
        layer_cls = (nn.remat(EncoderLayer, static_argnums=(3,))
                     if cfg.remat else EncoderLayer)
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, name="layer_{}".format(i))(
                x, attention_mask, deterministic, segments)

        # MLM head: transform + tied-free decoder to vocab (column-parallel).
        # With masked_positions [B, P] only those columns are projected
        # (mlm_logits [B, P, vocab]); loss-equivalent to the full head
        # because unmasked logits never enter the loss (see cfg.mlm_gather).
        if masked_positions is not None:
            xm = jnp.take_along_axis(x, masked_positions[:, :, None], axis=1)
            xm = with_logical(xm, ("batch", None, "act_embed"))
        else:
            xm = x
        h = nn.Dense(
            cfg.hidden_size, dtype=cfg.dtype,
            kernel_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed", "embed_out")),
            name="mlm_transform")(xm)
        h = nn.gelu(h, approximate=True)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="mlm_norm")(h)
        mlm_logits = nn.Dense(
            cfg.vocab_size, dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed", "vocab")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("vocab",)),
            name="mlm_decoder")(h)

        # NSP head over the [CLS] position(s): [B,0] unpacked, or every
        # packed sample's own [CLS] column.
        if cls_positions is None:
            cls_states = x[:, 0]                       # [B, H]
        else:
            cls_states = jnp.take_along_axis(           # [B, P, H]
                x, cls_positions[:, :, None], axis=1)
        pooled = nn.tanh(
            nn.Dense(
                cfg.hidden_size, dtype=cfg.dtype,
                kernel_init=nn.with_logical_partitioning(
                    _dense_init(cfg), ("embed", "embed_out")),
                name="pooler")(cls_states))
        nsp_logits = nn.Dense(
            2, dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed", None)),
            name="nsp_classifier")(pooled)
        return mlm_logits, nsp_logits


class BertForPreTrainingPacked(BertForPreTraining):
    """BertForPreTraining bound to the packed-batch key order (same params;
    see the base class docstring and loader/bert.py packed collate)."""

    BATCH_INPUTS = ("input_ids", "token_type_ids", "attention_mask",
                    "segments", "position_ids", "cls_positions")
