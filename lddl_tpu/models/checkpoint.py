"""Sharded checkpoint save/restore for TrainState (orbax).

The reference has NO model state files — its resume story is loader-side
recomputation by seeding (SURVEY.md §5: ``start_epoch``). lddl_tpu keeps
that loader contract and adds the other half a real training job needs:
the model/optimizer state, saved and restored AS SHARDS on an arbitrary
mesh (no host ever gathers the full state), via orbax.

The two halves compose into exact resume:

    state = restore_train_state(ckpt_dir, state_template, shardings)
    epoch = int(state.step) // steps_per_epoch
    loader = get_bert_pretrain_data_loader(..., start_epoch=epoch)

(orbax writes are atomic — a crash mid-save leaves the previous step
intact; ``keep`` bounds disk use.)

Compatibility: restore maps by tree structure. Round 2 moved the FFN
params from layer_i/{intermediate,ffn_output} to
layer_i/ffn/{intermediate,output}; round-1 checkpoints do not restore
against the current tree (pre-release break, no shim shipped).
"""

import jax
import numpy as np


import os


def _manager(ckpt_dir, keep=3, create=False):
    import orbax.checkpoint as ocp
    # orbax requires an absolute directory; a relative path (natural from
    # a CLI flag) would fail deep inside orbax at save/restore time.
    ckpt_dir = os.path.abspath(ckpt_dir)
    options = ocp.CheckpointManagerOptions(max_to_keep=keep, create=create)
    return ocp.CheckpointManager(ckpt_dir, options=options)


def save_train_state(ckpt_dir, state, keep=3):
    """Save ``state`` (a models.train.TrainState) at its current step.

    Writes shards from every process (call on ALL hosts of a multi-host
    mesh); blocks until the write is durable. Returns the saved step."""
    import orbax.checkpoint as ocp
    step = int(jax.device_get(state.step))
    mgr = _manager(ckpt_dir, keep=keep, create=True)
    # tx is static (not a pytree leaf); persist only the array state.
    payload = {"step": state.step, "params": state.params,
               "opt_state": state.opt_state}
    mgr.save(step, args=ocp.args.StandardSave(payload))
    mgr.close()  # waits for the async write
    return step


def latest_step(ckpt_dir):
    """Newest saved step under ``ckpt_dir``; None when the directory does
    not exist or holds no checkpoints. Read-only: never creates
    directories, and real I/O errors propagate."""
    if not os.path.isdir(ckpt_dir):
        return None
    mgr = _manager(ckpt_dir)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore_train_state(ckpt_dir, state_template, shardings, step=None):
    """Restore into the shapes/shardings of ``state_template`` (a
    TrainState from create_train_state — same model, same mesh; the
    restored arrays materialize directly as shards). Returns the restored
    TrainState."""
    import orbax.checkpoint as ocp
    if step is None:
        step = latest_step(ckpt_dir)
    if step is None:
        raise FileNotFoundError(
            "no checkpoint under {}".format(ckpt_dir))
    mgr = _manager(ckpt_dir)

    target = {
        "step": jax.ShapeDtypeStruct(state_template.step.shape,
                                     state_template.step.dtype,
                                     sharding=shardings.step),
        "params": jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            state_template.params, shardings.params),
        "opt_state": jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            state_template.opt_state, shardings.opt_state),
    }
    restored = mgr.restore(step, args=ocp.args.StandardRestore(target))
    mgr.close()
    # orbax may restore small leaves replicated; re-place everything onto
    # the exact target shardings (no-op where already correct).
    restored = {
        "step": jax.device_put(restored["step"], shardings.step),
        "params": jax.device_put(restored["params"], shardings.params),
        "opt_state": jax.device_put(restored["opt_state"],
                                    shardings.opt_state),
    }
    return state_template.replace(step=restored["step"],
                                  params=restored["params"],
                                  opt_state=restored["opt_state"])
