"""Shared multi-head attention for the model stack.

One implementation serves BERT self-attention, BART encoder/decoder
self-attention, and BART cross-attention — so the sharding annotations
(Megatron column/row-parallel over tp), the finite -1e9 masking invariant
(dtype-min overflows to -inf in bf16 and NaNs an all-masked row), and the
ring-attention opt-in live in exactly one place.

Ring attention (ops/ring_attention.py) engages when ``attention_impl ==
"ring"``, the call is self-attention (q_input is kv_input), there is no
extra additive bias (ring is bidirectional-full-attention only — causal
decoding stays dense), and the ambient mesh has sp > 1.
"""

from typing import Any, Optional

import jax.numpy as jnp
import flax.linen as nn

with_logical = nn.with_logical_constraint


def resolve_auto_impl(seq_len, blockwise_ok, attention_dropout,
                      deterministic=False, *, head_dim):
    """attention_impl="auto" -> "flash"|"dense" (measured selection,
    MODEL_BENCH.json). The round-5 single-block kernels
    (ops/flash_attention.py, fat (b, h)-row cells + one fused backward)
    made the pallas path win or tie everywhere from L = 256 up — incl.
    the reference's L=512 headline config that rounds 3-4 conceded to
    XLA's fused dense attention (bert_base 45.2 vs 42.2 wall MFU,
    bert_large parity within noise, round-5 chip probes), and the online
    kernels keep their long-L wins (L=1024: 36.3 vs 34.0; L=2048: 35.6
    vs 28.0, round 4). Dense stays ahead only at L <= 128 (52.1 vs 42.1
    at the shortest bin) where per-kernel-launch overhead dominates.
    The former in-between band (512 < L_pad < 1024, where the ONLINE
    kernels lose — L=768 in-model probe 33.9 vs 38.1) was taken by
    extending the single-block kernels to l_pad <= 896 with one-row
    cells: kernel-level 1.71x over dense at L=768 and 1.51x at L=896,
    in-model 46.4 vs 38.7 MFU at L=768 (FLASH_ATTENTION_BENCH.json /
    MODEL_BENCH.json), so only L_pad <= 128 remains dense at the
    standard head_dim 64 (wider heads keep the 512 bound — _use_onekv).
    Flash is picked
    only when it computes the SAME math as dense (it skips
    attention-prob dropout, so dropout > 0 pins dense — unless the call
    is deterministic, where dropout is a no-op and flash is identical):
    auto never changes the trained model, only the speed."""
    from ..ops.flash_attention import pad_seq_len, single_block_serves

    effective_dropout = 0.0 if deterministic else attention_dropout
    # single_block_serves is the dispatcher's own predicate (incl. its
    # head-dim gate), so the selector can never promise the single-block
    # regime where flash_attention would fall back to the online kernels.
    return ("flash" if blockwise_ok and effective_dropout == 0.0
            and (single_block_serves(seq_len, head_dim)
                 or pad_seq_len(seq_len) >= 1024) else "dense")


class MultiHeadAttention(nn.Module):
    """softmax(QK^T/sqrt(d) + bias) V with logical-axis sharding.

    ``padding_mask``: [B, Lk] key validity (1 = attend), or None.
    ``extra_bias``: optional additive [*, Lq, Lk] term (e.g. causal).
    Child params are named query/key/value/output, so wrapping modules
    keep stable checkpoint trees.
    """

    hidden_size: int
    num_heads: int
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0
    initializer_range: float = 0.02
    attention_impl: str = "dense"

    @nn.compact
    def __call__(self, q_input, kv_input, padding_mask, deterministic,
                 extra_bias: Optional[Any] = None,
                 segments: Optional[Any] = None):
        head_dim = self.hidden_size // self.num_heads
        init = nn.initializers.normal(stddev=self.initializer_range)

        def proj(name):
            # Column-parallel: the flat (heads*head_dim) output dim shards
            # over tp ("heads"); reshaped to [B, L, H, D] after.
            return nn.Dense(
                self.num_heads * head_dim, dtype=self.dtype,
                kernel_init=nn.with_logical_partitioning(
                    init, ("embed", "heads")),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), ("heads",)),
                name=name)

        def split_heads(t, seq_ax):
            t = t.reshape(t.shape[0], t.shape[1], self.num_heads, head_dim)
            return with_logical(t, ("batch", seq_ax, "heads", "kv"))

        # Blockwise impls serve bidirectional self-attention with a plain
        # padding mask; causal/cross calls always take the dense path.
        blockwise_ok = (q_input is kv_input and extra_bias is None
                        and padding_mask is not None)
        impl = self.attention_impl
        if impl == "auto":
            impl = resolve_auto_impl(q_input.shape[1], blockwise_ok,
                                     self.dropout, deterministic,
                                     head_dim=head_dim)
        use_ring = False
        if impl == "ring" and blockwise_ok:
            from ..parallel.compat import get_abstract_mesh
            mesh = get_abstract_mesh()
            use_ring = "sp" in mesh.axis_names and mesh.shape["sp"] > 1
        if segments is not None and use_ring:
            # Packing serves SHORT samples; ring serves LONG sequences —
            # the combination has no use case, so fail loudly rather than
            # silently attending across packed samples.
            raise NotImplementedError(
                "packed sequences (segments) are not supported with ring "
                "attention; use attention_impl='flash' or 'dense'")

        if use_ring:
            # Sequence stays sharded: Q/K/V keep the "seq" axis on sp and
            # K/V blocks rotate around the ring. Attention-prob dropout is
            # skipped under ring (standard for blockwise kernels).
            from ..ops.ring_attention import ring_attention

            q = split_heads(proj("query")(q_input), "seq")
            k = split_heads(proj("key")(kv_input), "seq")
            v = split_heads(proj("value")(kv_input), "seq")
            ctx = ring_attention(q, k, v, padding_mask, mesh)
        elif impl == "flash" and blockwise_ok:
            # The pallas fused kernel (ops/flash_attention.py); attention-
            # prob dropout is skipped, like ring. Packed rows hand the
            # kernel per-token segment ids — the block-diagonal mask is
            # enforced inside the kernel, no L x L mask materializes.
            from ..ops.flash_attention import flash_attention

            q = split_heads(proj("query")(q_input), None)
            k = split_heads(proj("key")(kv_input), None)
            v = split_heads(proj("value")(kv_input), None)
            if segments is not None:
                ctx = flash_attention(q, k, v, segments=segments)
            else:
                ctx = flash_attention(q, k, v, padding_mask)
        else:
            # Full-sequence attention: entering this block the activations
            # all-gather from sp, and heads shard over tp.
            q = split_heads(proj("query")(q_input), None)
            k = split_heads(proj("key")(kv_input), None)
            v = split_heads(proj("value")(kv_input), None)

            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
                head_dim).astype(self.dtype)
            # Finite large-negative (not dtype-min): fp32 min overflows to
            # -inf in bf16, and an all-masked row would softmax to NaN.
            bias = 0.0
            if segments is not None:
                # Packed rows: block-diagonal — attend only same-segment,
                # non-pad keys (subsumes the padding mask).
                allowed = ((segments[:, None, :, None]
                            == segments[:, None, None, :])
                           & (segments[:, None, None, :] > 0))
                bias = jnp.where(allowed, 0.0, -1e9)
            elif padding_mask is not None:
                bias = jnp.where(padding_mask[:, None, None, :] > 0, 0.0,
                                 -1e9)
            if extra_bias is not None:
                bias = bias + extra_bias
            probs = nn.softmax(scores + jnp.asarray(bias, self.dtype),
                               axis=-1)
            probs = nn.Dropout(self.dropout)(probs,
                                             deterministic=deterministic)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)

        ctx = ctx.reshape(ctx.shape[0], ctx.shape[1],
                          self.num_heads * head_dim)
        # Row-parallel: input dim sharded over tp, XLA psums the output.
        out = nn.Dense(
            self.hidden_size, dtype=self.dtype,
            kernel_init=nn.with_logical_partitioning(
                init, ("heads", "embed")),
            name="output")(ctx)
        return with_logical(out, ("batch", "seq", "act_embed"))


class FeedForward(nn.Module):
    """Column-parallel expand (gelu) + row-parallel contract — the one
    transformer MLP both model families use (children:
    intermediate/output)."""

    hidden_size: int
    intermediate_size: int
    dtype: Any = jnp.bfloat16
    initializer_range: float = 0.02

    @nn.compact
    def __call__(self, x):
        init = nn.initializers.normal(stddev=self.initializer_range)
        h = nn.Dense(
            self.intermediate_size, dtype=self.dtype,
            kernel_init=nn.with_logical_partitioning(
                init, ("embed", "mlp")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("mlp",)),
            name="intermediate")(x)
        h = nn.gelu(h, approximate=True)
        return nn.Dense(
            self.hidden_size, dtype=self.dtype,
            kernel_init=nn.with_logical_partitioning(
                init, ("mlp", "embed")),
            name="output")(h)
