"""SARIF 2.1.0 export so findings render inline in code-review tooling.

Only new (non-baselined, non-suppressed) findings become ``results`` —
the SARIF artifact answers "what does this change introduce", the same
contract as the exit code. Baselined findings are emitted with
``baselineState: "unchanged"`` so reviewers can still see the
grandfathered debt without it gating anything.
"""

SARIF_VERSION = "2.1.0"
_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
           "master/Schemata/sarif-schema-2.1.0.json")


def _result(finding, baseline_state=None):
    # Plain repo-relative URIs: consumers (GitHub code scanning, IDE
    # SARIF viewers) resolve them against the checkout they run in.
    r = {
        "ruleId": finding.rule,
        "level": "error" if baseline_state is None else "note",
        "message": {"text": finding.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": finding.path},
                "region": {"startLine": finding.line,
                           "startColumn": max(1, finding.col + 1)},
            },
        }],
    }
    if baseline_state is not None:
        r["baselineState"] = baseline_state
    return r


def to_sarif(report, rules):
    """SARIF log dict for a :class:`core.Report` under ``rules``."""
    return {
        "$schema": _SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "lddl-check",
                    "rules": [
                        {"id": r.id,
                         "shortDescription": {"text": r.doc}}
                        for r in sorted(rules, key=lambda r: r.id)
                    ],
                },
            },
            "results": (
                [_result(f) for f in report.new]
                + [_result(f, "unchanged") for f in report.baselined]
            ),
            "invocations": [{
                "executionSuccessful": report.ok,
            }],
        }],
    }
