"""Concrete determinism / SPMD-safety rules.

Each rule encodes one pipeline invariant (SURVEY §0, ``utils/rng.py``
contract). The table in README's "Static analysis" section is generated
from the ``id`` + ``doc`` attributes here — keep both one-line accurate.
"""

import ast

from .core import Finding, Rule, register, _match_any

# --------------------------------------------------------------- global-rng

# Module-level functions of CPython's ``random`` that draw from the hidden
# global Mersenne state. ``random.Random(seed)`` instances are allowed: the
# seed is explicit, so determinism is auditable at the call site.
_PY_RANDOM_FUNCS = frozenset({
    "seed", "random", "randint", "randrange", "uniform", "shuffle",
    "choice", "choices", "sample", "gauss", "normalvariate", "betavariate",
    "expovariate", "getrandbits", "randbytes", "triangular",
    "lognormvariate", "vonmisesvariate", "paretovariate", "weibullvariate",
})


@register
class GlobalRngRule(Rule):
    id = "global-rng"
    doc = ("no global-state RNG (random.*, np.random.* module functions, "
           "np.random.default_rng) in pipeline code — use the keyed "
           "utils.rng streams (world_rng/worker_rng/sample_rng)")
    allow = ("lddl_tpu/utils/rng.py", "lddl_tpu/models/testing.py")

    def run(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve_call(node)
            if not name:
                continue
            if name.startswith("numpy.random."):
                attr = name.split(".", 2)[2]
                if attr == "Generator" or attr == "Philox":
                    # Explicitly-keyed constructions (what utils.rng itself
                    # builds on) are the sanctioned escape hatch.
                    continue
                yield ctx.finding(
                    self.id, node,
                    "{}() is process-global or ad-hoc-seeded RNG; derive a "
                    "stream from utils.rng (world_rng/worker_rng/"
                    "sample_rng) so every rank draws identically".format(
                        name))
            elif name.startswith("random."):
                attr = name.split(".", 1)[1]
                if attr in _PY_RANDOM_FUNCS:
                    yield ctx.finding(
                        self.id, node,
                        "random.{}() draws from CPython's hidden global "
                        "state; use a keyed utils.rng stream".format(attr))


# --------------------------------------------------------------- wall-clock

_WALL_CLOCK = frozenset({
    "time.time", "time.time_ns", "time.localtime", "time.gmtime",
    "time.ctime", "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
})


@register
class WallClockRule(Rule):
    id = "wall-clock"
    doc = ("no wall-clock (time.time, datetime.now) feeding data-shaping "
           "decisions; observability timestamps and benchmarks are "
           "allowlisted, log-only uses carry inline suppressions")
    # Trace timestamps are the one legitimate wall-clock consumer;
    # benchmarks measure wall time by definition; lease deadlines are
    # wall-clock by design (lease-isolation guards what matters there).
    # Observability files are allowlisted INDIVIDUALLY — autoscale.py is
    # deliberately absent: scaling decisions must derive from the fleet
    # aggregate, never from a clock read of its own.
    allow = ("lddl_tpu/observability/registry.py",
             "lddl_tpu/observability/tracing.py",
             "lddl_tpu/observability/exporters.py",
             "lddl_tpu/observability/fleet.py",
             "lddl_tpu/observability/series.py",
             "lddl_tpu/observability/alerts.py",
             "lddl_tpu/observability/__init__.py",
             "benchmarks/*",
             "lddl_tpu/resilience/leases.py")

    def run(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = ctx.resolve_call(node)
                if name in _WALL_CLOCK:
                    yield ctx.finding(
                        self.id, node,
                        "{}() is wall-clock; if this value can reach shard "
                        "bytes, names, or iteration order it diverges "
                        "ranks — use a seeded stream or a monotonic timer, "
                        "or suppress with a justification if log-only"
                        .format(name))


# ----------------------------------------------------------- atomic-publish

_MOVE_FUNCS = frozenset({"os.replace", "os.rename", "os.renames",
                         "shutil.move"})
# Packages that publish into shard directories: a raw write-mode open()
# there can leave a torn file that a resume or a reader will trust.
_SHARD_PKGS = ("lddl_tpu/preprocess/*", "lddl_tpu/balance/*",
               "lddl_tpu/loader/*", "lddl_tpu/resilience/*",
               "lddl_tpu/ingest/*", "lddl_tpu/utils/fs.py")


def _open_mode(node):
    """The mode string of an open() call, or None when not a literal."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


@register
class AtomicPublishRule(Rule):
    id = "atomic-publish"
    doc = ("all publishes into shard dirs go through resilience.io "
           "(atomic_write/atomic_publish/write_table_atomic): flags "
           "os.replace/os.rename/shutil.move anywhere, raw "
           "pq.write_table and write-mode open() in pipeline packages")
    # backend.py is the object-store half of the sanctioned publisher:
    # its raw opens/links/replaces ARE the multipart-upload-then-commit
    # machinery the rest of the tree must route through.
    allow = ("lddl_tpu/resilience/io.py",
             "lddl_tpu/resilience/backend.py")

    def run(self, ctx):
        in_shard_pkg = _match_any(ctx.path, _SHARD_PKGS)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve_call(node)
            if name in _MOVE_FUNCS:
                yield ctx.finding(
                    self.id, node,
                    "raw {}() re-opens the torn-publish window; route "
                    "through resilience.io.atomic_write/atomic_publish "
                    "(tmp + fsync + replace + dir fsync)".format(name))
            elif (name == "pyarrow.parquet.write_table"
                  and ctx.path.startswith("lddl_tpu/")):
                yield ctx.finding(
                    self.id, node,
                    "raw pq.write_table() publishes a shard without "
                    "tmp+fsync+replace; use "
                    "resilience.io.write_table_atomic")
            elif name == "open" and in_shard_pkg:
                mode = _open_mode(node)
                if mode is None or any(c in mode for c in "wax"):
                    yield ctx.finding(
                        self.id, node,
                        "write-mode open({!r}) in a shard-publishing "
                        "package; a crash mid-write leaves a torn file — "
                        "use resilience.io.atomic_write".format(mode))


# ------------------------------------------------------- unsorted-iteration

_LIST_FUNCS = frozenset({"os.listdir", "os.scandir", "os.walk",
                         "glob.glob", "glob.iglob"})
# Consumers whose result cannot depend on the input order.
_ORDER_INSENSITIVE = frozenset({"sorted", "len", "set", "frozenset", "sum",
                                "min", "max", "any", "all"})


@register
class UnsortedIterationRule(Rule):
    id = "unsorted-iteration"
    doc = ("os.listdir/glob.glob/os.walk results are filesystem-ordered; "
           "they must pass through sorted() (or an order-insensitive "
           "reduction) before anything downstream can observe the order")

    def run(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve_call(node)
            if name not in _LIST_FUNCS:
                continue
            if self._order_insensitive(ctx, node):
                continue
            yield ctx.finding(
                self.id, node,
                "{}() returns entries in filesystem order, which differs "
                "across hosts and filesystems; wrap the result in "
                "sorted() so shard enumeration order is a pure function "
                "of the names".format(name))

    @staticmethod
    def _order_insensitive(ctx, node):
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.Call):
                name = ctx.resolve_call(anc)
                if name in _ORDER_INSENSITIVE:
                    return True
            if isinstance(anc, ast.SetComp):
                # A set comprehension erases input order by construction.
                return True
            if isinstance(anc, ast.stmt):
                # Stop at the enclosing statement: a later sorted() on the
                # stored variable is invisible to this (deliberately
                # syntactic) check — sort at the producer instead.
                return False
        return False


# --------------------------------------------------------- swallowed-error

_OS_ERRORS = frozenset({"OSError", "IOError", "EnvironmentError",
                        "os.error"})


@register
class SwallowedErrorRule(Rule):
    id = "swallowed-error"
    doc = ("no bare `except:` and no `except OSError: pass` — transient "
           "I/O errors must route through resilience.with_retries (or be "
           "suppressed with a why-comment when best-effort is the intent)")
    # resilience/io.py IS the error-routing layer; its internal best-effort
    # cleanups (tmp unlink in finally, dir-fsync on FAT/FUSE) are the
    # audited exception — backend.py's staging/GC cleanups likewise.
    allow = ("lddl_tpu/resilience/io.py",
             "lddl_tpu/resilience/backend.py")

    def run(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield ctx.finding(
                    self.id, node,
                    "bare `except:` swallows SystemExit/KeyboardInterrupt "
                    "and every bug; name the exceptions (transient I/O "
                    "belongs in resilience.with_retries)")
                continue
            if not self._catches_oserror(ctx, node.type):
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                yield ctx.finding(
                    self.id, node,
                    "`except OSError: pass` silently swallows I/O "
                    "failure; retry via resilience.with_retries, surface "
                    "it, or suppress with a justification if best-effort")

    @staticmethod
    def _catches_oserror(ctx, type_node):
        nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
            else [type_node]
        for n in nodes:
            if ctx.resolve_name(n) in _OS_ERRORS:
                return True
        return False


# -------------------------------------------------------------- stage-span

# Stage entry points that must open their top-level span(s) so every
# trace carries the stage skeleton (span names are stable API — README
# table). Migrated from the grep lint in tests/test_observability.py;
# the elastic claim loop and the streaming-ingest service joined when
# fleet telemetry made their spans part of the cross-host merged trace.
STAGE_SPANS = {
    "lddl_tpu/preprocess/runner.py": ("preprocess.run",),
    "lddl_tpu/preprocess/steal.py": ("preprocess.gather",
                                     "preprocess.finalize"),
    "lddl_tpu/balance/balancer.py": ("balance.run",),
    "lddl_tpu/loader/dataloader.py": ("loader.epoch",),
    "lddl_tpu/ingest/incremental.py": ("ingest.run",),
}


@register
class StageSpanRule(Rule):
    id = "stage-span"
    doc = ("each pipeline stage entry file must open its top-level "
           "obs.span (preprocess.run / preprocess.gather+finalize / "
           "balance.run / loader.epoch / ingest.run) so traces always "
           "carry the stage skeleton")
    only = tuple(STAGE_SPANS)

    def run(self, ctx):
        wanted = STAGE_SPANS.get(ctx.path)
        if not wanted:
            return
        found = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve_call(node)
            if not name or not (name == "span" or name.endswith(".span")):
                continue
            if (node.args and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value in wanted):
                found.add(node.args[0].value)
        for want in wanted:
            if want in found:
                continue
            # Required-pattern rule: no single node is "the" violation, so
            # the finding anchors to line 1 of the file.
            yield Finding(self.id, ctx.path, 1, 0,
                          "stage entry point lacks its top-level "
                          "span(\"{}\") — traces from this stage lose "
                          "their skeleton".format(want), ctx.snippet_at(1))


# --------------------------------------------------------- jit-host-effect

_HOST_CLOCKS = frozenset({"time.time", "time.time_ns", "time.perf_counter",
                          "time.monotonic", "time.process_time"})


@register
class JitHostEffectRule(Rule):
    id = "jit-host-effect"
    doc = ("no host side-effects (print, observability hooks, "
           "float(tracer), host clocks) inside jax.jit-compiled function "
           "bodies — they fire at trace time only, or crash")
    only = ("lddl_tpu/ops/*", "lddl_tpu/models/*")

    def run(self, ctx):
        jitted = self._jitted_function_names(ctx)
        if not jitted:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef) \
                    or node.name not in jitted:
                continue
            for f in self._scan_body(ctx, node):
                yield f

    def _scan_body(self, ctx, func):
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve_call(node)
            if not name:
                continue
            if name == "print":
                yield ctx.finding(
                    self.id, node,
                    "print() inside a jit-compiled function runs once at "
                    "trace time, never per step; use jax.debug.print or "
                    "hoist it out")
            elif name.split(".")[0] == "observability" \
                    or name.startswith("observability."):
                yield ctx.finding(
                    self.id, node,
                    "metrics/tracing hook {}() inside a jit-compiled "
                    "function fires at trace time only; record outside "
                    "the jitted region".format(name))
            elif name in _HOST_CLOCKS:
                yield ctx.finding(
                    self.id, node,
                    "host clock {}() inside a jit-compiled function reads "
                    "once at trace time; time outside the jitted region"
                    .format(name))
            elif name == "float" and len(node.args) == 1 \
                    and not isinstance(node.args[0], ast.Constant):
                yield ctx.finding(
                    self.id, node,
                    "float(...) on a traced value forces a host sync (or "
                    "crashes under jit); keep values as jax arrays inside "
                    "the compiled region")

    @staticmethod
    def _jitted_function_names(ctx):
        """Names of functions compiled by jax.jit in this module: directly
        decorated, passed to a jax.jit(...) call, or reached through one
        ``functools.partial(f, ...)`` hop (the idiom ops/masking.py uses)."""
        partial_targets = {}  # local name -> set of function names
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                callee = ctx.resolve_call(node.value)
                if callee in ("functools.partial", "partial") \
                        and node.value.args \
                        and isinstance(node.value.args[0], ast.Name):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            partial_targets.setdefault(tgt.id, set()).add(
                                node.value.args[0].id)
        jitted = set()

        def note_jit_arg(arg):
            if isinstance(arg, ast.Name):
                jitted.add(arg.id)
                jitted.update(partial_targets.get(arg.id, ()))
            elif isinstance(arg, ast.Call):
                callee = ctx.resolve_call(arg)
                if callee in ("functools.partial", "partial") and arg.args \
                        and isinstance(arg.args[0], ast.Name):
                    jitted.add(arg.args[0].id)

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and ctx.resolve_call(node) == "jax.jit" and node.args:
                note_jit_arg(node.args[0])
            elif isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        callee = ctx.resolve_call(dec)
                        if callee == "jax.jit":
                            jitted.add(node.name)
                        elif callee in ("functools.partial", "partial") \
                                and dec.args \
                                and ctx.resolve_name(dec.args[0]) \
                                == "jax.jit":
                            jitted.add(node.name)
                    elif ctx.resolve_name(dec) == "jax.jit":
                        jitted.add(node.name)
        return jitted


# --------------------------------------------------- manifest-determinism

_NONDET_IN_MANIFEST = frozenset(
    {"os.getpid", "uuid.uuid1", "uuid.uuid4", "time.monotonic",
     "time.perf_counter"} | _WALL_CLOCK)


@register
class ManifestDeterminismRule(Rule):
    id = "manifest-determinism"
    doc = ("functions that build .manifest.json / ledger / ingest-journal "
           "content must not draw wall-clock, pids, uuids, or RNG — "
           "resume compares these bytes across runs and ranks, and the "
           "ingest journal additionally promises content-hash-only "
           "document identity")
    # Lease records legitimately carry wall-clock deadlines and per-host
    # ids; they are scheduling state under _leases/, never resume-compared
    # content (the lease-isolation flow rule guards the real boundary).
    allow = ("lddl_tpu/resilience/leases.py",)

    # Builder-name tokens this rule guards: manifest/ledger (PR 4) plus
    # the streaming-ingestion record builders (journal segments, intake
    # records, generation meta) and the offline packer's manifest-meta
    # fragment (pack_meta_of — packed row shapes are resume-compared
    # manifest content too).
    NAME_TOKENS = ("manifest", "ledger", "journal", "intake", "generation",
                   "pack_meta")

    def run(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            lowered = node.name.lower()
            if not any(tok in lowered for tok in self.NAME_TOKENS):
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                name = ctx.resolve_call(call)
                if not name:
                    continue
                if name in _NONDET_IN_MANIFEST \
                        or name.startswith("numpy.random.") \
                        or (name.startswith("random.")
                            and name.split(".", 1)[1] in _PY_RANDOM_FUNCS):
                    yield ctx.finding(
                        self.id, call,
                        "{}() inside manifest/ledger builder {}(): this "
                        "content is compared byte-for-byte across runs "
                        "and ranks on resume; nondeterministic fields "
                        "poison it".format(name, node.name))


# ------------------------------------------------------------ python-hot-loop

# Methods that materialize per-element Python objects out of Arrow/numpy
# containers. On the loader's per-sample path each call site multiplies by
# tokens-per-epoch; the schema-v2 columnar decode exists precisely so none
# of these run per token.
_PY_MATERIALIZERS = frozenset({"as_py", "to_pylist", "to_pydict", "tolist"})


@register
class PythonHotLoopRule(Rule):
    id = "python-hot-loop"
    doc = ("no per-token Python iteration on the loader, preprocess, or "
           "balance hot paths (.as_py()/.to_pylist()/.to_pydict()/"
           ".tolist(), nested-generator np.fromiter over token streams) "
           "— stay columnar; justified fallbacks carry suppressions")
    # Loader per-sample work multiplies by epochs; preprocess/balance
    # per-token work multiplies by corpus bytes (the ROADMAP's native
    # preprocess item starts by making these loops visible).
    only = ("lddl_tpu/loader/*", "lddl_tpu/preprocess/*",
            "lddl_tpu/balance/*")

    def run(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in _PY_MATERIALIZERS:
                yield ctx.finding(
                    self.id, node,
                    ".{}() materializes one Python object per element; on "
                    "a pipeline hot path that is per-token work per epoch "
                    "(loader) or per corpus byte (preprocess/balance) — "
                    "decode Arrow list<int32> columns to numpy views "
                    "(loader.bert._list_views), keep numpy arrays "
                    "columnar, or move the work offline; suppress with a "
                    "justification for once-per-process tables, debug "
                    "sinks, or v1 fallbacks".format(func.attr))
                continue
            name = ctx.resolve_call(node)
            if name == "numpy.fromiter" and node.args:
                gen = node.args[0]
                if isinstance(gen, ast.GeneratorExp) \
                        and len(gen.generators) > 1:
                    yield ctx.finding(
                        self.id, node,
                        "np.fromiter over a nested generator iterates per "
                        "TOKEN in Python (outer per-sample, inner per-"
                        "element); consume schema-v2 id columns or batch "
                        "the conversion — baseline only the schema-v1 "
                        "text fallback")
