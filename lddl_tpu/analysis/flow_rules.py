"""The four interprocedural flow rules.

Each subsumes a syntactic ancestor in :mod:`.rules` by catching the
*helper-laundered* variant the ancestor cannot see: the syntactic rules
fire when a guarded pattern appears inside one function; the flow rules
fire when the pattern's **value or effect crosses a function (or
module-global) boundary** before reaching a sink. Same-function flows are
deliberately NOT reported here — that keeps the two rule families
non-overlapping, so one violation produces one finding.

These are ``scope = "project"`` rules: :func:`core.run_check` builds the
whole-tree :mod:`.project` model, extracts per-file dataflow facts
(cached by content hash), runs the :mod:`.dataflow` fixpoint once, and
routes each emitted finding through the rule whose id it carries — so
``allow`` lists, ``--rules`` filters, inline suppressions, and the
baseline all behave exactly as they do for syntactic rules.
"""

from .core import Rule, _match_any, register
from . import dataflow

# Shard-publishing packages (mirrors rules._SHARD_PKGS): call sites here
# must only publish through resilience.io.
SHARD_PKGS = ("lddl_tpu/preprocess/*", "lddl_tpu/balance/*",
              "lddl_tpu/loader/*", "lddl_tpu/resilience/*",
              "lddl_tpu/ingest/*", "lddl_tpu/utils/fs.py")

# The sanctioned atomic publishers: io.py's internals ARE the
# tmp+fsync+replace dance and backend.py's ARE the object-store
# multipart-upload-then-commit dance; effects never propagate out of
# them. A raw write laundered AROUND the backend is a finding.
SANCTIONED = ("lddl_tpu/resilience/io.py",
              "lddl_tpu/resilience/backend.py")

# Files whose raw writes never land in shard directories by construction
# (trace/metrics files and the fleet-telemetry spools under .telemetry/,
# generated C++ build trees, pre-pipeline downloads, the analyzer's own
# cache, test-only fault latches, merged-trace/report artifacts from the
# status tools) — excluded as publish-path effect SOURCES so a
# shard-package call into them is not a publish violation. A raw shard
# write anywhere else on a shard-package call path is still caught
# (fixture-pinned in tests/test_dataflow.py).
PUBLISH_SOURCE_EXEMPT = (
    "lddl_tpu/observability/*", "lddl_tpu/analysis/*", "lddl_tpu/native/*",
    "lddl_tpu/download/*", "lddl_tpu/resilience/faults.py",
    "tools/pipeline_status.py", "tools/trace_summary.py",
    "tools/bench_trajectory.py",
)


class FlowRule(Rule):
    """Base for project-scope rules: run via the dataflow engine, not per
    file. ``run`` is unused; ``applies_to`` still gates findings by the
    finding's path."""

    scope = "project"

    def run(self, ctx):  # pragma: no cover - project rules don't run here
        return ()


@register
class WallClockFlowRule(FlowRule):
    id = "wall-clock-flow"
    doc = ("flow-aware wall-clock: clock/pid/uuid/hostname values that "
           "reach manifest/ledger content or publish arguments through "
           "any helper chain (subsumes wall-clock across functions)")
    # Observability files are allowlisted INDIVIDUALLY — autoscale.py is
    # deliberately absent so the analyzer proves scale decisions are
    # clock-free (derived from the fleet aggregate only).
    allow = ("lddl_tpu/observability/registry.py",
             "lddl_tpu/observability/tracing.py",
             "lddl_tpu/observability/exporters.py",
             "lddl_tpu/observability/fleet.py",
             "lddl_tpu/observability/__init__.py",
             "benchmarks/*",
             # tmp-file names embed the pid on purpose: the pre-publish
             # scratch name is never part of the published state (same
             # for backend.py's upload ids and part names — staging
             # identity, never object content).
             "lddl_tpu/resilience/io.py",
             "lddl_tpu/resilience/backend.py",
             # Lease deadlines/holder ids are wall-clock BY DESIGN (the
             # one cross-host time base a shared FS offers); the
             # lease-isolation rule — not this one — guards the boundary
             # that matters: lease state never reaches shard bytes.
             "lddl_tpu/resilience/leases.py")


@register
class RngFlowRule(FlowRule):
    id = "rng-flow"
    doc = ("flow-aware RNG: draws on unkeyed generators "
           "(np.random.default_rng() / random.Random() with no key) that "
           "were laundered through helpers or module globals before "
           "shaping data (subsumes global-rng across functions)")
    allow = ("lddl_tpu/models/testing.py",)


@register
class FsOrderFlowRule(FlowRule):
    id = "fs-order-flow"
    doc = ("flow-aware FS order: listdir/glob/walk results that cross a "
           "function boundary and are then iterated, indexed, or rendered "
           "into strings/error text without an intervening sorted() "
           "(subsumes unsorted-iteration across functions)")
    allow = ()


@register
class PublishPathFlowRule(FlowRule):
    id = "publish-path-flow"
    doc = ("flow-aware atomic publish: shard-package call paths that "
           "reach a raw write (write-mode open, pq.write_table) in a "
           "helper OUTSIDE the shard packages without passing through "
           "resilience.io (subsumes atomic-publish across functions). "
           "Models the async-sink writer-thread boundary: a callable "
           "enqueued via preprocess/sink.py is treated as called at the "
           "enqueue site (dataflow.DEFERRED_CALL_MODULE_SUFFIXES), so "
           "deferring a raw write cannot launder it past the rule")
    allow = ("lddl_tpu/resilience/io.py",
             "lddl_tpu/resilience/backend.py")


@register
class LeaseIsolationRule(FlowRule):
    id = "lease-isolation"
    doc = ("lease state (holder id, epoch, deadline) returned by "
           "resilience.leases must never flow into shard bytes or "
           ".manifest.json content — lease files themselves and the "
           "_done fence records are the only sanctioned sinks (the "
           "latter carry inline suppressions)")
    # No blanket allowances: the lease module's internal writes are
    # exempted at the engine level (dataflow.LEASE_MODULE), and the one
    # legitimate epoch-into-record flow in preprocess/steal.py is a
    # why-commented inline suppression.
    allow = ()


FLOW_RULE_IDS = ("wall-clock-flow", "rng-flow", "fs-order-flow",
                 "publish-path-flow", "lease-isolation")


def run_flow_analysis(module_facts):
    """Phase B over cached/extracted per-file facts. Returns
    ``[(rule_id, path, lineno, message)]`` BEFORE allow-list, suppression
    and baseline filtering (core.run_check applies those)."""
    return dataflow.analyze_modules(
        module_facts,
        shard_pkg=lambda p: _match_any(p, SHARD_PKGS),
        publish_source_ok=lambda p: not _match_any(
            p, PUBLISH_SOURCE_EXEMPT),
        sanctioned=lambda p: _match_any(p, SANCTIONED),
    ).findings
