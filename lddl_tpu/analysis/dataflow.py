"""Interprocedural taint engine behind the flow rules.

Four taint *kinds* track values whose presence in pipeline output breaks
the determinism contract (PAPER §0: byte-stable manifests, rank-identical
RNG, FS-order-independent enumeration):

``wallclock``
    wall time, pids, uuids, hostnames — anything that differs across runs
    or ranks. Sinks: manifest/ledger builder content, publish arguments.
``rng``
    draws from *unkeyed* random state (``random.random``,
    ``np.random.default_rng()`` with no key, an unseeded
    ``random.Random()``). Sinks: draw methods on a tainted generator in
    pipeline code, publish arguments.
``fsorder``
    the *ordering* of ``os.listdir``/``glob``/``os.walk`` results. Clears
    through ``sorted()`` / order-insensitive reductions; sinks are
    order-observing uses (iteration, indexing, string interpolation,
    error text, publish arguments).
``lease``
    scheduling state from :mod:`lddl_tpu.resilience.leases` (holder ids,
    epochs, wall-clock deadlines). Sources are synthesized in phase B:
    the return value of ANY call into the lease module is lease-tainted
    (and counts as boundary-crossing by construction). Sinks: publish
    arguments and manifest/ledger builder content — leases decide WHO
    runs a unit, and nothing about the winner may reach shard bytes or
    ``.manifest.json``. The lease module's own file writes are exempt
    (lease files ARE lease state; they live in ``_leases/``, never a
    shard directory).

A fourth analysis is an *effect* propagation, not value taint:
``publish-path`` marks every function that transitively performs a raw
(non-atomic) file write, so a shard-package call into a helper that
bypasses ``resilience.io`` is caught no matter where the helper lives.

How it works
------------

Phase A (per file, cacheable): each function body is abstract-interpreted
once into a serializable *fact* record. Expressions evaluate to taint
**terms** — unions of atoms::

    ["src", kind, name, path, lineno]   taint introduced here
    ["param", i]                        the function's i-th parameter
    ["call", qualname, [args...], ln]   result of a resolved project call
    ["ext", name, [args...]]            result of an unresolved call
    ["san", [kinds...], term]           sanitizer applied (clears kinds)
    ["elem", term]                      element-of (clears fsorder: an
                                        element carries no ordering)
    ["global", modname, name]           module-global read

Sink sites record the term that reached them; resolved calls record their
argument terms; raw writes record their location. Nothing here depends on
other files, so facts cache by content hash.

Phase B (global, cheap): per-function summaries — which kinds the return
value carries, which params pass through to the return, which params
reach a sink — are iterated to a fixpoint across the call graph, then
every sink term is evaluated under the final summaries. A finding is
emitted only when the taint **crossed a function or module-global
boundary**: same-function flows are the syntactic rules' territory and
stay out of the flow rules' output.
"""

import ast

# ------------------------------------------------------------ vocabulary

KINDS = ("wallclock", "rng", "fsorder", "lease")

RULE_ID_OF_KIND = {
    "wallclock": "wall-clock-flow",
    "rng": "rng-flow",
    "fsorder": "fs-order-flow",
    "lease": "lease-isolation",
}
PUBLISH_PATH_RULE = "publish-path-flow"

# The lease protocol module: calls into it yield lease-tainted values
# (phase B synthesizes the source), and its OWN publish calls are not
# shard publishes (lease files live under _leases/, deliberately written
# with the atomic primitives but never part of the dataset).
LEASE_MODULE = "lddl_tpu/resilience/leases.py"

# Writer-thread boundary modules (the async shard sink): a callable
# passed INTO any function of these modules is deferred execution — the
# sink's writer thread will call it later. Phase A synthesizes a call
# edge at the enqueue site for every function-valued argument (named
# function references AND lambda bodies), so the publish-path effect
# analysis sees "enqueue -> deferred publish" as a call chain and a raw
# ``pq.write_table``/write-mode ``open`` laundered through
# ``ShardWriter.submit`` is caught exactly like a direct call
# (fixture-pinned in tests/test_dataflow.py). Matched by suffix so test
# fixtures can exercise the boundary with their own sink module copies.
DEFERRED_CALL_MODULE_SUFFIXES = ("preprocess/sink.py",)

# Method names that enqueue a callable for deferred execution. The async
# sink's entry point is ``ShardWriter.submit`` — a method on a LOCAL
# value, which dotted resolution cannot bind to the sink module, so the
# method NAME is the trigger. concurrent.futures ``pool.submit`` matches
# too, which is sound for the effect analysis (pool workers really do
# run the submitted function) and precision-neutral in practice (only
# function-REFERENCE arguments synthesize edges; call-result arguments
# are already modeled).
DEFERRED_METHOD_NAMES = frozenset({"submit"})


def _is_deferred_call_module(path):
    p = (path or "").replace("\\", "/")
    return any(p.endswith(s) for s in DEFERRED_CALL_MODULE_SUFFIXES)

_WALLCLOCK_SOURCES = frozenset({
    "time.time", "time.time_ns", "time.localtime", "time.gmtime",
    "time.ctime", "time.strftime", "datetime.datetime.now",
    "datetime.datetime.utcnow", "datetime.datetime.today",
    "datetime.date.today", "os.getpid", "os.getppid", "uuid.uuid1",
    "uuid.uuid4", "socket.gethostname", "platform.node",
    "threading.get_ident",
})

# CPython random-module functions drawing from hidden global state.
_PY_RANDOM_FUNCS = frozenset({
    "seed", "random", "randint", "randrange", "uniform", "shuffle",
    "choice", "choices", "sample", "gauss", "normalvariate", "betavariate",
    "expovariate", "getrandbits", "randbytes", "triangular",
    "lognormvariate", "vonmisesvariate", "paretovariate", "weibullvariate",
})

_FS_SOURCES = frozenset({
    "os.listdir", "os.scandir", "os.walk", "glob.glob", "glob.iglob",
})

# Order-insensitive consumers / order-erasing constructions: clear fsorder.
_FS_SANITIZERS = frozenset({
    "sorted", "set", "frozenset", "len", "sum", "min", "max", "any", "all",
    "collections.Counter",
})

# Externals whose result preserves input iteration order (everything else
# unknown drops fsorder taint to keep the rule's precision high; wallclock
# and rng taint flow through ALL externals).
_ORDER_PRESERVING = frozenset({
    "list", "tuple", "reversed", "iter", "enumerate", "zip", "filter",
    "map", "itertools.chain", "itertools.islice",
})

# Draw methods: calling one of these on an rng-tainted receiver uses the
# unkeyed stream to shape data.
_DRAW_METHODS = frozenset({
    "random", "randint", "integers", "choice", "choices", "shuffle",
    "permutation", "permuted", "uniform", "normal", "standard_normal",
    "sample", "bytes", "gauss", "randrange", "getrandbits",
})

# Publish functions: (name suffix) -> indices of arguments whose content
# or name lands in a shard directory. atomic_publish's arg 0 is the
# pre-publish temp name (pid-tagged scratch) and deliberately not a sink.
_PUBLISH_SINKS = {
    "atomic_write": (0, 1),
    "write_table_atomic": (0, 1),
    "atomic_publish": (1,),
    "json.dump": (0,),
}

# Raw-write operations for the publish-path effect analysis.
_MOVE_FUNCS = frozenset({"os.replace", "os.rename", "os.renames",
                         "shutil.move"})


def _open_write_mode(node):
    """Mode string of a write-mode open() call, or None when read-only or
    not a literal-mode open."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return None  # default "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value if any(c in mode.value for c in "wax") else None
    return "?"  # dynamic mode: treat as a potential write


# ----------------------------------------------------------- term algebra
#
# Terms are plain nested lists so they JSON-serialize into the cache.


def _union(*terms):
    out = []
    for t in terms:
        for atom in t:
            if atom not in out:
                out.append(atom)
    return out


def _src(kind, name, path, lineno):
    return ["src", kind, name, path, lineno]


# --------------------------------------------------------- fact extraction


class _FunctionFacts(object):
    """Serializable phase-A record for one function."""

    def __init__(self, qualname, name, cls, path, lineno, params):
        self.qualname = qualname
        self.name = name
        self.cls = cls
        self.path = path
        self.lineno = lineno
        self.params = params
        self.returns = []  # term
        # [{"kinds": [...], "what": str, "lineno": int, "term": term}]
        self.sinks = []
        # [{"callee": qualname, "args": [term-or-None per param],
        #   "lineno": int}]
        self.calls = []
        self.raw_writes = []  # [{"op": str, "lineno": int}]

    def to_dict(self):
        return {"qualname": self.qualname, "name": self.name,
                "cls": self.cls, "path": self.path, "lineno": self.lineno,
                "params": self.params, "returns": self.returns,
                "sinks": self.sinks, "calls": self.calls,
                "raw_writes": self.raw_writes}

    @classmethod
    def from_dict(cls, d):
        ff = cls(d["qualname"], d["name"], d["cls"], d["path"], d["lineno"],
                 d["params"])
        ff.returns = d["returns"]
        ff.sinks = d["sinks"]
        ff.calls = d["calls"]
        ff.raw_writes = d["raw_writes"]
        return ff


class _ModuleFacts(object):
    def __init__(self, path, modname):
        self.path = path
        self.modname = modname
        self.functions = []  # [_FunctionFacts]
        self.globals = {}  # name -> term

    def to_dict(self):
        return {"path": self.path, "modname": self.modname,
                "functions": [f.to_dict() for f in self.functions],
                "globals": self.globals}

    @classmethod
    def from_dict(cls, d):
        mf = cls(d["path"], d["modname"])
        mf.functions = [_FunctionFacts.from_dict(f) for f in d["functions"]]
        mf.globals = d["globals"]
        return mf


def extract_module_facts(project, module):
    """Phase A for one module: facts for every function plus module-global
    assignment terms."""
    mf = _ModuleFacts(module.path, module.modname)
    top = _Extractor(project, module, facts=None, cls=None)
    for name, value in sorted(module.global_assigns.items()):
        mf.globals[name] = top.eval_expr(value)
    for local in sorted(module.functions):
        fi = module.functions[local]
        ff = _FunctionFacts(fi.qualname, fi.name, fi.cls, module.path,
                            fi.lineno, fi.params)
        ex = _Extractor(project, module, facts=ff, cls=fi.cls)
        env = {}
        for i, p in enumerate(fi.params):
            env[p] = [["param", i]]
        ex.run_body(fi.node.body, env)
        mf.functions.append(ff)
    return mf


class _Extractor(object):
    """One pass over a function body collecting terms, sinks and calls.

    Loops are processed twice so loop-carried taint propagates; branches
    are processed sequentially on one environment (flow-lite union)."""

    def __init__(self, project, module, facts, cls):
        self.project = project
        self.module = module
        self.facts = facts  # None at module top level
        self.cls = cls
        # Builder contexts whose content is resume-compared byte-for-byte:
        # manifest/ledger (PR 4) plus the ingest record builders (journal
        # segments, intake records, generation meta) — keep in sync with
        # rules.ManifestDeterminismRule.NAME_TOKENS.
        self._manifest_ctx = bool(
            facts is not None
            and any(tok in facts.name.lower()
                    for tok in ("manifest", "ledger", "journal", "intake",
                                "generation")))

    # ------------------------------------------------------- statements

    def run_body(self, stmts, env):
        for stmt in stmts:
            self.run_stmt(stmt, env)

    def run_stmt(self, stmt, env):
        if isinstance(stmt, ast.Assign):
            t = self.eval_expr(stmt.value, env)
            for tgt in stmt.targets:
                self._bind_target(tgt, t, env)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._bind_target(stmt.target,
                                  self.eval_expr(stmt.value, env), env)
        elif isinstance(stmt, ast.AugAssign):
            t = self.eval_expr(stmt.value, env)
            if isinstance(stmt.target, ast.Name):
                env[stmt.target.id] = _union(
                    env.get(stmt.target.id, []), t)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self.eval_expr(stmt.iter, env)
            self._sink(["fsorder"], "iterated in a for-loop", stmt.iter, it)
            self._bind_target(stmt.target, [["elem", it]] if it else [],
                              env)
            for _ in range(2):
                self.run_body(stmt.body, env)
            self.run_body(stmt.orelse, env)
        elif isinstance(stmt, ast.While):
            self.eval_expr(stmt.test, env)
            for _ in range(2):
                self.run_body(stmt.body, env)
            self.run_body(stmt.orelse, env)
        elif isinstance(stmt, ast.If):
            self.eval_expr(stmt.test, env)
            self.run_body(stmt.body, env)
            self.run_body(stmt.orelse, env)
        elif isinstance(stmt, ast.Try):
            self.run_body(stmt.body, env)
            for h in stmt.handlers:
                self.run_body(h.body, env)
            self.run_body(stmt.orelse, env)
            self.run_body(stmt.finalbody, env)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                t = self.eval_expr(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind_target(item.optional_vars, t, env)
            self.run_body(stmt.body, env)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None and self.facts is not None:
                t = self.eval_expr(stmt.value, env)
                self.facts.returns = _union(self.facts.returns, t)
                if self._manifest_ctx:
                    self._sink(["wallclock", "rng", "lease"],
                               "returned from manifest/ledger builder "
                               "{}()".format(self.facts.name),
                               stmt, t)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                t = self.eval_expr(stmt.exc, env)
                self._sink(["fsorder"], "rendered into error text",
                           stmt, t)
        elif isinstance(stmt, ast.Expr):
            # In-place sort sanitizes the sorted name.
            v = stmt.value
            if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                    and v.func.attr == "sort" \
                    and isinstance(v.func.value, ast.Name):
                name = v.func.value.id
                if env.get(name):
                    env[name] = [["san", ["fsorder"], env[name]]]
                for a in v.args:
                    self.eval_expr(a, env)
            else:
                self.eval_expr(v, env)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested function: its effects belong to the enclosing
            # function; params are unknown (empty terms).
            inner = dict(env)
            for a in stmt.args.posonlyargs + stmt.args.args:
                inner[a.arg] = []
            self.run_body(stmt.body, inner)
        elif isinstance(stmt, ast.ClassDef):
            pass
        elif isinstance(stmt, (ast.Delete, ast.Import, ast.ImportFrom,
                               ast.Global, ast.Nonlocal, ast.Pass,
                               ast.Break, ast.Continue)):
            pass
        elif isinstance(stmt, ast.Assert):
            self.eval_expr(stmt.test, env)

    def _bind_target(self, tgt, term, env):
        if isinstance(tgt, ast.Name):
            env[tgt.id] = term
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._bind_target(el, [["elem", term]] if term else [],
                                  env)
        elif isinstance(tgt, ast.Starred):
            self._bind_target(tgt.value, term, env)
        elif isinstance(tgt, ast.Subscript):
            # d[k] = v: taint the container; in manifest builders the
            # stored value is manifest content.
            if self._manifest_ctx:
                self._sink(["wallclock", "rng", "lease"],
                           "stored into manifest/ledger content in "
                           "{}()".format(self.facts.name), tgt, term)
            base = tgt.value
            if isinstance(base, ast.Name):
                env[base.id] = _union(env.get(base.id, []), term)
        elif isinstance(tgt, ast.Attribute):
            path = self._attr_path(tgt)
            if path is not None:
                env[path] = term

    @staticmethod
    def _attr_path(node):
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return ".".join(reversed(parts))

    # ------------------------------------------------------ expressions

    def eval_expr(self, node, env=None):
        env = env if env is not None else {}
        if node is None or isinstance(node, ast.Constant):
            return []
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            if node.id in self.module.global_assigns:
                return [["global", self.module.modname, node.id]]
            return []
        if isinstance(node, ast.Attribute):
            path = self._attr_path(node)
            if path is not None and path in env:
                return env[path]
            return self.eval_expr(node.value, env)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.BinOp):
            return _union(self.eval_expr(node.left, env),
                          self.eval_expr(node.right, env))
        if isinstance(node, ast.BoolOp):
            return _union(*[self.eval_expr(v, env) for v in node.values])
        if isinstance(node, ast.UnaryOp):
            return self.eval_expr(node.operand, env)
        if isinstance(node, ast.Compare):
            return _union(self.eval_expr(node.left, env),
                          *[self.eval_expr(c, env)
                            for c in node.comparators])
        if isinstance(node, ast.IfExp):
            self.eval_expr(node.test, env)
            return _union(self.eval_expr(node.body, env),
                          self.eval_expr(node.orelse, env))
        if isinstance(node, ast.Subscript):
            v = self.eval_expr(node.value, env)
            self._sink(["fsorder"], "indexed by position", node, v)
            s = self.eval_expr(node.slice, env)
            return _union([["elem", v]] if v else [], s)
        if isinstance(node, (ast.List, ast.Tuple)):
            return _union(*[self.eval_expr(e, env) for e in node.elts])
        if isinstance(node, ast.Set):
            inner = _union(*[self.eval_expr(e, env) for e in node.elts])
            return [["san", ["fsorder"], inner]] if inner else []
        if isinstance(node, ast.Dict):
            parts = [self.eval_expr(k, env) for k in node.keys
                     if k is not None]
            parts += [self.eval_expr(v, env) for v in node.values]
            t = _union(*parts)
            if self._manifest_ctx and t:
                self._sink(["wallclock", "rng", "lease"],
                           "placed in manifest/ledger content in "
                           "{}()".format(self.facts.name), node, t)
            return t
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp,
                             ast.DictComp)):
            return self._eval_comp(node, env)
        if isinstance(node, ast.JoinedStr):
            t = _union(*[self.eval_expr(v, env) for v in node.values])
            self._sink(["fsorder"], "interpolated into a string", node, t)
            return t
        if isinstance(node, ast.FormattedValue):
            return self.eval_expr(node.value, env)
        if isinstance(node, ast.Starred):
            return self.eval_expr(node.value, env)
        if isinstance(node, ast.Await):
            return self.eval_expr(node.value, env)
        if isinstance(node, ast.NamedExpr):
            t = self.eval_expr(node.value, env)
            self._bind_target(node.target, t, env)
            return t
        if isinstance(node, ast.Lambda):
            return []
        return []

    def _eval_comp(self, node, env):
        inner = dict(env)
        iter_terms = []
        for gen in node.generators:
            it = self.eval_expr(gen.iter, inner)
            iter_terms.append(it)
            self._bind_target(gen.target, [["elem", it]] if it else [],
                              inner)
            for cond in gen.ifs:
                self.eval_expr(cond, inner)
        if isinstance(node, ast.DictComp):
            elt = _union(self.eval_expr(node.key, inner),
                         self.eval_expr(node.value, inner))
        else:
            elt = self.eval_expr(node.elt, inner)
        result = _union(elt, *iter_terms)
        if isinstance(node, ast.SetComp) and result:
            return [["san", ["fsorder"], result]]
        return result

    # ------------------------------------------------------------ calls

    def _eval_call(self, node, env):
        arg_terms = [self.eval_expr(a, env) for a in node.args]
        kw_terms = {kw.arg: self.eval_expr(kw.value, env)
                    for kw in node.keywords}
        all_args = _union(*(arg_terms + list(kw_terms.values())))
        lineno = node.lineno

        dotted = self.project.resolve_dotted(self.module, node.func)

        # A dotted chain rooted at a LOCAL value (``g.shuffle(...)``,
        # ``self._rng.uniform(...)``, a module-global generator) is a
        # method call on data, not a reference to an importable name —
        # resolve_dotted can't know that, so detect it here.
        base = node.func
        while isinstance(base, ast.Attribute):
            base = base.value
        local_receiver = (
            isinstance(node.func, ast.Attribute)
            and isinstance(base, ast.Name)
            and (base.id in env or base.id in self.module.global_assigns)
            and base.id not in self.module.aliases)
        fi = None
        if dotted is not None and not local_receiver:
            fi = self.project.resolve_function(self.module, dotted,
                                               cls=self.cls)
        if fi is None and dotted is not None and base is not node.func \
                and isinstance(base, ast.Name) and base.id == "self":
            # self.method() binds through the class even though ``self``
            # is also a local value.
            fi = self.project.resolve_function(self.module, dotted,
                                               cls=self.cls)
            local_receiver = fi is None

        # Publish sinks fire regardless of whether the publisher resolves
        # into the project (resilience.io) or not (fixtures, stubs). The
        # lease module is exempt: its "publishes" are the lease files
        # themselves (scheduling state under _leases/, not shard data),
        # and flagging them would make every legitimate lease operation a
        # caller-side finding.
        if dotted is not None and not local_receiver \
                and self.module.path != LEASE_MODULE:
            for suffix, positions in _PUBLISH_SINKS.items():
                if dotted == suffix or dotted.endswith("." + suffix):
                    for pos in positions:
                        t = arg_terms[pos] if pos < len(arg_terms) \
                            else None
                        if t:
                            self._sink(
                                KINDS,
                                "passed to {}() argument {} (published "
                                "into a shard directory)".format(suffix,
                                                                 pos),
                                node, t)
                    break

        # Raw-write effect sites (publish-path analysis).
        if self.facts is not None and dotted is not None \
                and not local_receiver:
            if dotted in _MOVE_FUNCS:
                self.facts.raw_writes.append(
                    {"op": "{}()".format(dotted), "lineno": lineno})
            elif dotted == "pyarrow.parquet.write_table":
                self.facts.raw_writes.append(
                    {"op": "pq.write_table()", "lineno": lineno})
            elif dotted == "open":
                mode = _open_write_mode(node)
                if mode is not None:
                    self.facts.raw_writes.append(
                        {"op": "open(mode={!r})".format(mode),
                         "lineno": lineno})

        # Project-resolved call: record the edge with per-param arg terms
        # (fi.node is None for cache-stub modules; the callee's facts come
        # from the cache, so the edge still resolves).
        if fi is not None:
            mapped = self._map_args(fi, node, arg_terms, kw_terms)
            if self.facts is not None:
                self.facts.calls.append({"callee": fi.qualname,
                                         "args": mapped, "lineno": lineno})
                if _is_deferred_call_module(fi.path):
                    # Writer-thread boundary: callables handed to the
                    # async sink run later on its thread — synthesize the
                    # deferred call edges here (see the module constant).
                    self._record_deferred_callables(node, env)
            return [["call", fi.qualname, mapped, lineno]]

        # Method call on a local/global value or unresolvable receiver.
        if isinstance(node.func, ast.Attribute) \
                and (local_receiver or dotted is None):
            recv = self.eval_expr(node.func.value, env)
            attr = node.func.attr
            if attr in DEFERRED_METHOD_NAMES and self.facts is not None:
                # Writer-thread/executor boundary: the enqueued callable
                # runs later — synthesize its call edge at the enqueue
                # site so deferred effects stay on the call graph.
                self._record_deferred_callables(node, env)
            if attr in _DRAW_METHODS:
                self._sink(["rng"],
                           "drawn from via .{}() — data shaped by an "
                           "unkeyed stream".format(attr), node, recv)
            if attr == "join":
                self._sink(["fsorder"], "joined into a string", node,
                           all_args)
            if attr == "format":
                self._sink(["fsorder"], "formatted into a string", node,
                           all_args)
            return [["ext", "." + attr, [_union(recv, all_args)]]] \
                if (recv or all_args) else []

        if dotted is None:
            # Dynamic callee (local variable holding a function, etc.).
            return [["ext", "<dynamic>", [all_args]]] if all_args else []

        # Taint sources.
        src = self._source_kind(dotted, node)
        if src is not None:
            return _union([_src(src, dotted, self.module.path, lineno)],
                          all_args)

        # Sanitizers (fsorder).
        if dotted in _FS_SANITIZERS:
            return [["san", ["fsorder"], all_args]] if all_args else []

        # Unresolved external call.
        return [["ext", dotted, [all_args]]] if all_args else []

    @staticmethod
    def _source_kind(dotted, node):
        if dotted in _WALLCLOCK_SOURCES:
            return "wallclock"
        if dotted in _FS_SOURCES:
            return "fsorder"
        if dotted.startswith("random."):
            attr = dotted.split(".", 1)[1]
            if attr in _PY_RANDOM_FUNCS or attr == "SystemRandom":
                return "rng"
            if attr == "Random" and not node.args and not node.keywords:
                return "rng"  # unseeded instance
        if dotted == "os.urandom":
            return "rng"
        if dotted.startswith("numpy.random."):
            attr = dotted.split(".", 2)[2]
            if attr in ("Generator", "Philox", "PCG64", "SeedSequence"):
                return None  # explicit keying building blocks
            if attr == "default_rng":
                if not node.args and not node.keywords:
                    return "rng"  # unkeyed
                return None  # keyed: determinism auditable at the site
            return "rng"  # module-level global-state draws
        return None

    def _record_deferred_callables(self, node, env):
        """Synthesize call edges for function-valued arguments at a
        deferred-execution boundary (the async sink's enqueue): a named
        project function reference becomes a zero-arg call edge, and a
        lambda argument's body is walked in place so ITS calls and raw
        writes attribute to the enclosing (enqueuing) function — either
        way the publish-path fixpoint sees through the queue."""
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Lambda):
                inner = dict(env)
                a = arg.args
                for p in (a.posonlyargs + a.args + a.kwonlyargs):
                    inner[p.arg] = []
                self.eval_expr(arg.body, inner)
                continue
            if not isinstance(arg, (ast.Name, ast.Attribute)):
                continue
            dotted = self.project.resolve_dotted(self.module, arg)
            if dotted is None:
                continue
            fi = self.project.resolve_function(self.module, dotted,
                                               cls=self.cls)
            if fi is None:
                continue
            self.facts.calls.append({
                "callee": fi.qualname,
                "args": [None] * len(fi.params),
                "lineno": getattr(arg, "lineno", node.lineno)})

    def _map_args(self, fi, node, arg_terms, kw_terms):
        """Positional+keyword argument terms mapped onto the callee's
        parameter indices (None for params not passed). Bound-method calls
        (self.m(...) / obj.m(...)) shift past the self param."""
        mapped = [None] * len(fi.params)
        offset = 0
        if fi.cls is not None and fi.params[:1] == ["self"] \
                and isinstance(node.func, ast.Attribute):
            offset = 1
        for i, t in enumerate(arg_terms):
            j = i + offset
            if j < len(mapped):
                mapped[j] = t
        for name, t in kw_terms.items():
            if name in fi.params:
                mapped[fi.params.index(name)] = t
        return mapped

    # ------------------------------------------------------------- sinks

    def _sink(self, kinds, what, node, term):
        if self.facts is None or not term:
            return
        self.facts.sinks.append({"kinds": list(kinds), "what": what,
                                 "lineno": getattr(node, "lineno",
                                                   self.facts.lineno),
                                 "term": term})


# ------------------------------------------------------------- evaluation


class Summary(object):
    """Per-function, per-kind fixpoint state."""

    __slots__ = ("ret_srcs", "ret_params", "sink_params")

    def __init__(self):
        # kind -> frozenset of (name, path, lineno) source descriptors
        self.ret_srcs = {k: frozenset() for k in KINDS}
        # kind -> frozenset of param indices passed through to the return
        self.ret_params = {k: frozenset() for k in KINDS}
        # kind -> {param index: "what" description}
        self.sink_params = {k: {} for k in KINDS}

    def state(self):
        return (tuple(sorted((k, tuple(sorted(v)))
                             for k, v in self.ret_srcs.items())),
                tuple(sorted((k, tuple(sorted(v)))
                             for k, v in self.ret_params.items())),
                tuple(sorted((k, tuple(sorted(v.items())))
                             for k, v in self.sink_params.items())))


class _Taint(object):
    """One concrete taint reaching a point: a source descriptor plus
    whether it crossed a function/global boundary and through what."""

    __slots__ = ("name", "path", "lineno", "crossed", "via")

    def __init__(self, name, path, lineno, crossed, via):
        self.name = name
        self.path = path
        self.lineno = lineno
        self.crossed = crossed
        self.via = via  # qualname of the immediate boundary, or None

    def key(self):
        return (self.name, self.path, self.lineno)


class FlowResult(object):
    """Engine output: findings per rule id plus summaries for tests."""

    def __init__(self):
        self.findings = []  # [(rule_id, path, lineno, message)]
        self.summaries = {}


class Engine(object):
    """Phase B: fixpoint over function summaries, then finding emission."""

    def __init__(self, module_facts, max_iters=50):
        self.modules = {mf.modname: mf for mf in module_facts}
        self.functions = {}
        for mf in module_facts:
            for ff in mf.functions:
                self.functions[ff.qualname] = ff
        self.summaries = {q: Summary() for q in self.functions}
        self.max_iters = max_iters
        # publish-path effect: qualname -> (desc, path, lineno, via) | None
        self.raw_write_of = {}

    # -------------------------------------------------------- term eval

    def eval_term(self, term, kind, owner, _globals_seen=None):
        """Concrete taints (set of _Taint) and pass-through param indices
        carried by ``term`` for ``kind``, evaluated inside function facts
        ``owner`` under the current summaries."""
        out = {}
        params = set()

        def merge(sub, sp):
            out.update({t.key() + (t.crossed,): t for t in sub})
            params.update(sp)

        for atom in term:
            tag = atom[0]
            if tag == "src":
                if atom[1] == kind:
                    t = _Taint(atom[2], atom[3], atom[4], False, None)
                    out[t.key() + (t.crossed,)] = t
            elif tag == "param":
                params.add(atom[1])
            elif tag == "san":
                if kind not in atom[1]:
                    merge(*self.eval_term(atom[2], kind, owner,
                                          _globals_seen))
            elif tag == "elem":
                if kind != "fsorder":
                    merge(*self.eval_term(atom[1], kind, owner,
                                          _globals_seen))
            elif tag == "ext":
                if kind == "fsorder" and atom[1] not in _ORDER_PRESERVING \
                        and not atom[1].startswith("."):
                    continue
                for sub_term in atom[2]:
                    merge(*self.eval_term(sub_term, kind, owner,
                                          _globals_seen))
            elif tag == "global":
                mf = self.modules.get(atom[1])
                if mf is None:
                    continue
                seen = _globals_seen or set()
                gkey = (atom[1], atom[2])
                if gkey in seen:
                    continue
                gterm = mf.globals.get(atom[2])
                if gterm:
                    sub, sp = self.eval_term(gterm, kind, owner,
                                             seen | {gkey})
                    for t in sub:
                        # Module-global state crosses a scope boundary.
                        ct = _Taint(t.name, t.path, t.lineno, True,
                                    "module global {}".format(atom[2]))
                        out[ct.key() + (True,)] = ct
                    params |= sp
            elif tag == "call":
                callee, args = atom[1], atom[2]
                if kind == "lease":
                    callee_ff = self.functions.get(callee)
                    if callee_ff is not None \
                            and callee_ff.path == LEASE_MODULE:
                        # Synthesized source: anything returned by the
                        # lease module IS lease state. Crossing is true by
                        # construction (the value came out of leases.py).
                        t = _Taint(callee.split(".")[-1], callee_ff.path,
                                   atom[3], True, callee)
                        out[t.key() + (True,)] = t
                summ = self.summaries.get(callee)
                if summ is None:
                    for sub_term in args:
                        if sub_term is not None:
                            merge(*self.eval_term(sub_term, kind, owner,
                                                  _globals_seen))
                    continue
                for (name, path, ln) in summ.ret_srcs[kind]:
                    t = _Taint(name, path, ln, True, callee)
                    out[t.key() + (True,)] = t
                for j in summ.ret_params[kind]:
                    if j < len(args) and args[j] is not None:
                        sub, sp = self.eval_term(args[j], kind, owner,
                                                 _globals_seen)
                        for t in sub:
                            ct = _Taint(t.name, t.path, t.lineno, True,
                                        callee)
                            out[ct.key() + (True,)] = ct
                        params |= sp
        return set(out.values()), params

    def _emit_sink_param_findings(self, callee, args, lineno, kind, owner,
                                  emit):
        summ = self.summaries.get(callee)
        if summ is None:
            return
        for j, what in sorted(summ.sink_params[kind].items()):
            if j >= len(args) or args[j] is None:
                continue
            taints, _ = self.eval_term(args[j], kind, owner)
            for t in sorted(taints, key=lambda t: t.key()):
                emit(kind, owner.path, lineno,
                     "{src} ({spath}:{sline}) is passed into {callee}(), "
                     "where it is {what}".format(
                         src=t.name, spath=t.path, sline=t.lineno,
                         callee=callee.split(".")[-1], what=what))

    # ---------------------------------------------------------- fixpoint

    def solve(self):
        for _ in range(self.max_iters):
            changed = False
            for qual in sorted(self.functions):
                ff = self.functions[qual]
                summ = self.summaries[qual]
                before = summ.state()
                self._update_summary(ff, summ)
                if summ.state() != before:
                    changed = True
            if not changed:
                break

    def _update_summary(self, ff, summ):
        for kind in KINDS:
            taints, params = self.eval_term(ff.returns, kind, ff)
            summ.ret_srcs[kind] = summ.ret_srcs[kind] | {
                (t.name, t.path, t.lineno) for t in taints}
            summ.ret_params[kind] = summ.ret_params[kind] | params
            for sink in ff.sinks:
                if kind not in sink["kinds"]:
                    continue
                _, sp = self.eval_term(sink["term"], kind, ff)
                for j in sp:
                    summ.sink_params[kind].setdefault(j, sink["what"])
            # Transitive: an arg forwarded into a callee's sink param.
            for call in ff.calls:
                callee = self.summaries.get(call["callee"])
                if callee is None:
                    continue
                for j, what in callee.sink_params[kind].items():
                    if j >= len(call["args"]) or call["args"][j] is None:
                        continue
                    _, sp = self.eval_term(call["args"][j], kind, ff)
                    for i in sp:
                        summ.sink_params[kind].setdefault(
                            i, "{} (via {}())".format(
                                what, call["callee"].split(".")[-1]))

    # -------------------------------------------------- publish-path pass

    def solve_publish(self, source_ok, sanctioned):
        """Effect fixpoint: ``raw_write_of[qualname]`` = (op, path,
        lineno, via-or-None) for every function that transitively performs
        a raw write. ``source_ok(path)`` gates which files' local writes
        count (shard-package writes are the syntactic rule's job);
        ``sanctioned(path)`` names the atomic-publisher module(s) that
        never propagate the effect."""
        raw = {}
        for qual, ff in self.functions.items():
            if ff.raw_writes and source_ok(ff.path) \
                    and not sanctioned(ff.path):
                w = min(ff.raw_writes, key=lambda w: w["lineno"])
                raw[qual] = (w["op"], ff.path, w["lineno"], None)
        for _ in range(self.max_iters):
            changed = False
            for qual in sorted(self.functions):
                if qual in raw:
                    continue
                ff = self.functions[qual]
                if sanctioned(ff.path):
                    continue
                for call in ff.calls:
                    hit = raw.get(call["callee"])
                    if hit is None:
                        continue
                    callee_ff = self.functions.get(call["callee"])
                    if callee_ff is not None \
                            and sanctioned(callee_ff.path):
                        continue
                    raw[qual] = (hit[0], hit[1], hit[2], call["callee"])
                    changed = True
                    break
            if not changed:
                break
        self.raw_write_of = raw

    # ---------------------------------------------------------- findings

    def emit_findings(self, shard_pkg, sanctioned):
        """All flow findings: [(rule_id, path, lineno, message)].

        Value-taint findings fire at sinks whose taint crossed a
        boundary; publish-path findings fire at shard-package call sites
        whose callee (defined OUTSIDE the shard packages, where the
        syntactic atomic-publish rule cannot see) transitively raw-writes.
        """
        findings = []

        def emit(kind, path, lineno, message):
            findings.append((RULE_ID_OF_KIND[kind], path, lineno, message))

        for qual in sorted(self.functions):
            ff = self.functions[qual]
            for kind in KINDS:
                for sink in ff.sinks:
                    if kind not in sink["kinds"]:
                        continue
                    taints, _ = self.eval_term(sink["term"], kind, ff)
                    for t in sorted(taints, key=lambda t: t.key()):
                        if not t.crossed:
                            continue  # same-function: syntactic territory
                        if not t.via:
                            via = ""
                        elif "." in t.via:
                            via = " via {}()".format(t.via.split(".")[-1])
                        else:
                            via = " via {}".format(t.via)
                        emit(kind, ff.path, sink["lineno"],
                             "{src} ({spath}:{sline}){via} is {what}; "
                             "this value must not shape pipeline output"
                             .format(src=t.name, spath=t.path,
                                     sline=t.lineno, via=via,
                                     what=sink["what"]))
                # Call-site findings: tainted args into callee sink params.
                for call in ff.calls:
                    self._emit_sink_param_findings(
                        call["callee"], call["args"], call["lineno"],
                        kind, ff, emit)

            # publish-path-flow
            if shard_pkg(ff.path):
                for call in ff.calls:
                    hit = self.raw_write_of.get(call["callee"])
                    if hit is None:
                        continue
                    callee_ff = self.functions.get(call["callee"])
                    if callee_ff is None or shard_pkg(callee_ff.path):
                        continue  # syntactic atomic-publish territory
                    op, wpath, wline, via = hit
                    chain = "" if via is None else \
                        " (via {}())".format(via.split(".")[-1])
                    findings.append((
                        PUBLISH_PATH_RULE, ff.path, call["lineno"],
                        "call into {callee}(){chain} reaches a raw "
                        "{op} at {wpath}:{wline} without passing through "
                        "resilience.io; a crash there can publish a torn "
                        "file into a shard directory".format(
                            callee=call["callee"].split(".")[-1],
                            chain=chain, op=op, wpath=wpath,
                            wline=wline)))
        # Deterministic order + dedup (the same flow can be reached
        # through several call chains).
        seen = set()
        unique = []
        for f in sorted(findings, key=lambda f: (f[1], f[2], f[0], f[3])):
            key = (f[0], f[1], f[2])
            if key not in seen:
                seen.add(key)
                unique.append(f)
        return unique


def analyze_modules(module_facts, shard_pkg, publish_source_ok,
                    sanctioned):
    """Run phase B over extracted module facts. Returns a FlowResult."""
    engine = Engine(module_facts)
    engine.solve()
    engine.solve_publish(publish_source_ok, sanctioned)
    result = FlowResult()
    result.findings = engine.emit_findings(shard_pkg, sanctioned)
    result.summaries = engine.summaries
    return result
