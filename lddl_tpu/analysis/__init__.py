"""Static analysis for the pipeline's determinism & SPMD-safety invariants.

Public API::

    from lddl_tpu import analysis
    report = analysis.run_check(["lddl_tpu", "tools", "benchmarks"])
    assert report.ok, [f.format() for f in report.new]

CLI: ``python -m tools.lddl_check [paths...] [--json]`` — exits nonzero on
any finding not in the checked-in baseline
(``tools/lddl_check_baseline.json``) and not suppressed inline with
``# lddl: disable=<rule>``.
"""

from .core import (  # noqa: F401
    ANALYSIS_VERSION,
    DEFAULT_BASELINE,
    DEFAULT_CACHE,
    Finding,
    REPO_ROOT,
    Report,
    Rule,
    all_rules,
    analyze_source,
    baseline_entry,
    get_rules,
    iter_python_files,
    load_baseline,
    register,
    run_check,
    split_baselined,
)
from . import rules  # noqa: F401  (imports register the syntactic rules)
from . import flow_rules  # noqa: F401  (registers the flow rules)
from . import concurrency  # noqa: F401  (registers the concurrency rules)
from . import dataflow, project  # noqa: F401  (taint engine + model)
from .sarif import to_sarif  # noqa: F401

RULE_IDS = tuple(r.id for r in all_rules())
