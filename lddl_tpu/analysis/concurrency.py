"""Concurrency invariant analysis: thread escape, lock discipline,
signal safety, and env-read-after-spawn.

The pipeline's byte-identity contract (PAPER §: identical shards
regardless of worker/thread count) rests on a small set of concurrency
invariants that PR 10/12/18 established by hand: shared mutable state
crossing a thread boundary is lock-guarded, signal handlers only touch
reentrant locks and never block, and worker configuration is pinned
BEFORE the pool spawns. This module machine-checks them, reusing the
:mod:`.project` model and the phase-A/phase-B split of :mod:`.dataflow`:

- **Phase A** (:func:`extract_module_facts`) walks each parsed module
  once and records serializable per-function facts — module-global
  writes with the lexically-held locks, lock acquisitions and their
  nesting, resolved calls, thread-boundary hand-offs
  (``threading.Thread(target=...)``, ``.submit(fn)``), signal-handler
  registrations, pool/thread spawn points, and ``LDDL_TPU_*`` env reads.
  Nested functions and lambdas become pseudo-functions
  (``outer.<locals>.inner``) so a handler or thread target defined
  inline is its own call-graph node. Facts ride the content-hash cache
  exactly like dataflow facts.
- **Phase B** (:func:`run_concurrency_analysis`) builds the whole-tree
  call graph from the facts and emits findings for the four rules
  below. Findings route through ``core.run_check`` so ``allow`` lists,
  inline suppressions, ``--rules`` filters, and the baseline all apply.

Rules (ids match the README table):

- ``thread-escape`` — a mutable module global written on both sides of
  a thread boundary with at least one write not under a recognized
  lock. "Recognized" is lexical ``with <lock>:`` plus a must-hold
  entry-lock analysis (a helper only ever called under the lock counts
  as guarded), and mutation THROUGH a parameter is tracked (passing the
  global to a helper that mutates its argument unlocked is a write).
- ``lock-order`` — two locks acquired in both orders on some pair of
  call paths (the classic AB/BA deadlock), or a non-reentrant
  ``threading.Lock`` re-acquired while already held.
- ``signal-safety`` — from every ``signal.signal(...)``-registered
  handler: acquiring a non-reentrant ``threading.Lock`` (the bug class
  PR 10 fixed by switching the telemetry registries to RLock), or a
  blocking call (write-mode ``open``, ``queue.put`` without timeout,
  zero-arg ``.join()``, ``time.sleep``) on the handler path. The
  observability package's flush-on-TERM file writes are sanctioned at
  the engine level (flushing IS the handler's purpose; every frame is
  wrapped in best-effort try/except) — the non-reentrant-lock class is
  never sanctioned.
- ``env-read-after-spawn`` — an ``LDDL_TPU_*`` env read that happens
  after a process-pool spawn point on the same call path (workers
  snapshot the env at spawn, so late reads silently desynchronize
  parent and worker configuration — the class of bug the PR 18 runner
  pre-sizing dodged by hand). Plain-thread spawns only arm the
  same-function window: threads share the live environ, so only the
  tight spawn-then-read pattern is suspicious there. Reads inside
  observability/faults are exempt sources (telemetry gating reads env
  by design, once per hook).
"""

import ast

from .core import Rule, register

# Modules whose env reads are NOT env-read-after-spawn sources: the
# telemetry/faults gates read their own env switches on every hook by
# design (one lookup when disabled — the inertness contract), and none
# of those switches configure spawned workers.
ENV_SOURCE_EXEMPT_PREFIXES = ("lddl_tpu/observability/",
                              "lddl_tpu/resilience/faults.py")

# Blocking-call findings (signal-safety) are sanctioned on the
# flush-on-SIGTERM write machinery: the observability package (flushing
# IS the handler path's purpose and every frame is best-effort
# try/except), resilience/io.py (the atomic_write/open_append layer
# those flushes go through — its fsync/replace/retry-sleep ARE the
# sanctioned write), and resilience/faults.py (the test-only injection
# layer whose injected sleeps/write-errors trace the same hooks). Lock
# findings are never sanctioned — a non-reentrant lock deadlocks no
# matter how careful the I/O around it is.
SIGNAL_BLOCKING_SANCTIONED_PREFIXES = ("lddl_tpu/observability/",
                                       "lddl_tpu/resilience/io.py",
                                       "lddl_tpu/resilience/faults.py")

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
    "multiprocessing.Lock": "Lock",
    "multiprocessing.RLock": "RLock",
}

_MUTABLE_CTORS = frozenset({
    "dict", "list", "set", "bytearray",
    "collections.defaultdict", "collections.deque",
    "collections.OrderedDict", "collections.Counter",
})

# Container methods that mutate their receiver in place.
_MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "clear", "update",
    "setdefault", "add", "discard", "popitem", "appendleft", "extendleft",
    "rotate", "sort", "reverse",
})

_THREAD_CTORS = frozenset({"threading.Thread", "threading.Timer"})

_POOL_CTORS = frozenset({
    "concurrent.futures.ProcessPoolExecutor",
    "concurrent.futures.process.ProcessPoolExecutor",
    "multiprocessing.Pool",
    "multiprocessing.pool.Pool",
})

# Thread-pool executors spawn threads, not processes: they arm the
# boundary for thread-escape (via .submit) but not the env-snapshot
# hazard.
_THREAD_POOL_CTORS = frozenset({
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.thread.ThreadPoolExecutor",
})

# Callable-handoff method names that cross a thread boundary: the
# stdlib executors' and the async sink's submit (dataflow treats sink
# submits the same way — DEFERRED_METHOD_NAMES).
_SUBMIT_METHODS = frozenset({"submit"})

_ENV_READ_FUNCS = frozenset({"os.environ.get", "os.getenv",
                             "os.environ.setdefault"})

_BLOCKING_FUNCS = frozenset({"time.sleep", "os.replace", "os.rename",
                             "os.fsync", "shutil.move"})


# --------------------------------------------------------------- facts


class _CFuncFacts(object):
    """Serializable phase-A concurrency record for one function (or one
    nested pseudo-function)."""

    __slots__ = ("qualname", "name", "cls", "path", "lineno",
                 "writes", "param_writes", "acquires", "calls",
                 "spawns", "env_reads", "thread_targets",
                 "signal_handlers", "blocking")

    def __init__(self, qualname, name, cls, path, lineno):
        self.qualname = qualname
        self.name = name
        self.cls = cls
        self.path = path
        self.lineno = lineno
        # [{"g": global id, "lineno": int, "held": [lock ids]}]
        self.writes = []
        # [{"i": param index, "lineno": int, "held": [lock ids]}]
        self.param_writes = []
        # [{"lock": lock id, "lineno": int, "held": [outer lock ids]}]
        self.acquires = []
        # [{"callee": qualname or None, "dotted": str or None,
        #   "lineno": int, "held": [...], "args_globals": {str(i): gid}}]
        self.calls = []
        # [{"kind": "pool"|"thread", "lineno": int}]
        self.spawns = []
        # [{"name": env var, "lineno": int}]
        self.env_reads = []
        self.thread_targets = []  # [{"target": qualname, "lineno": int}]
        self.signal_handlers = []  # [{"target": qualname, "lineno": int}]
        # [{"what": str, "lineno": int}]
        self.blocking = []

    def to_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    @classmethod
    def from_dict(cls, d):
        ff = cls(d["qualname"], d["name"], d["cls"], d["path"],
                 d["lineno"])
        for k in ("writes", "param_writes", "acquires", "calls", "spawns",
                  "env_reads", "thread_targets", "signal_handlers",
                  "blocking"):
            setattr(ff, k, d[k])
        return ff


class _CModuleFacts(object):
    """Phase-A concurrency facts for one module."""

    def __init__(self, path, modname):
        self.path = path
        self.modname = modname
        self.functions = []  # [_CFuncFacts]
        # global name -> {"lineno": int, "mutable": bool}
        self.globals = {}
        # lock id ("mod.name" or "mod.Cls.attr") -> kind ("Lock"/"RLock"/..)
        self.locks = {}

    def to_dict(self):
        return {"path": self.path, "modname": self.modname,
                "functions": [f.to_dict() for f in self.functions],
                "globals": self.globals, "locks": self.locks}

    @classmethod
    def from_dict(cls, d):
        mf = cls(d["path"], d["modname"])
        mf.functions = [_CFuncFacts.from_dict(f) for f in d["functions"]]
        mf.globals = d["globals"]
        mf.locks = d["locks"]
        return mf


# ---------------------------------------------------------- extraction


def _is_mutable_init(module, project, value):
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        dotted = project.resolve_dotted(module, value.func)
        return dotted in _MUTABLE_CTORS
    return False


def extract_module_facts(project, module):
    """Phase A for one module: concurrency facts for every function
    (methods and nested defs included) plus the module's mutable-global
    and lock registries."""
    mf = _CModuleFacts(module.path, module.modname)

    for name, value in sorted(module.global_assigns.items()):
        dotted = None
        if isinstance(value, ast.Call):
            dotted = project.resolve_dotted(module, value.func)
        if dotted in _LOCK_CTORS:
            mf.locks["{}.{}".format(module.modname, name)] = \
                _LOCK_CTORS[dotted]
            continue
        mf.globals[name] = {
            "lineno": value.lineno,
            "mutable": _is_mutable_init(module, project, value),
        }

    # Instance locks: ``self.attr = threading.Lock()`` anywhere in a
    # class's methods registers "mod.Cls.attr" so ``with self.attr:``
    # resolves in every method of the class.
    for local in sorted(module.functions):
        fi = module.functions[local]
        if fi.cls is None or fi.node is None:
            continue
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            dotted = project.resolve_dotted(module, node.value.func)
            if dotted not in _LOCK_CTORS:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    lock_id = "{}.{}.{}".format(module.modname, fi.cls,
                                                tgt.attr)
                    mf.locks[lock_id] = _LOCK_CTORS[dotted]

    for local in sorted(module.functions):
        fi = module.functions[local]
        _extract_function(project, module, mf, fi.node, fi.qualname,
                          fi.name, fi.cls,
                          [a.arg for a in (fi.node.args.posonlyargs
                                           + fi.node.args.args)])
    return mf


def _extract_function(project, module, mf, node, qualname, name, cls,
                      params):
    ff = _CFuncFacts(qualname, name, cls, module.path, node.lineno)
    mf.functions.append(ff)
    ex = _CExtractor(project, module, mf, ff, cls, params)
    body = node.body if isinstance(node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) \
        else [ast.Expr(value=node.body)]  # lambda
    ex.run_body(body, held=())
    # Nested defs/lambdas become their own pseudo-functions AFTER the
    # parent walk (the walk recorded the call/hand-off edges to them).
    for child, child_name, child_params in ex.nested:
        _extract_function(project, module, mf, child,
                          "{}.<locals>.{}".format(qualname, child_name),
                          child_name, cls, child_params)


class _CExtractor(object):
    """One pass over a function body collecting concurrency events.

    Tracks the lexically-held lock set through ``with`` statements and a
    local-shadow set so a plain local named like a module global is not
    miscounted as a global write."""

    def __init__(self, project, module, mf, facts, cls, params):
        self.project = project
        self.module = module
        self.mf = mf
        self.facts = facts
        self.cls = cls
        self.params = list(params)
        self.globals_decl = set()
        self.local_shadow = set(params)
        self.nested = []  # [(ast node, pseudo name, params)]
        self._nested_names = {}  # local name -> pseudo qualname
        self._lambda_n = 0

    # ----------------------------------------------------- resolution

    def _pseudo_qual(self, child_name):
        return "{}.<locals>.{}".format(self.facts.qualname, child_name)

    def resolve_dotted(self, expr):
        return self.project.resolve_dotted(self.module, expr)

    def global_id_of(self, expr):
        """Absolute id of the module-global an expression names, or
        None. Bare names resolve against THIS module (minus local
        shadows); dotted names resolve through import aliases so
        ``fleet._hb`` from another module and ``_hb`` inside fleet.py
        produce the same id."""
        if isinstance(expr, ast.Name):
            n = expr.id
            if n in self.local_shadow and n not in self.globals_decl:
                return None
            if n in self.mf.globals or n in self.globals_decl:
                return "{}.{}".format(self.module.modname, n)
            return None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id in self.local_shadow:
                return None
            return self.resolve_dotted(expr)
        return None

    def lock_id_of(self, expr):
        """Lock id a ``with``-subject names, or None: a module-global
        lock (here or in an imported module) or ``self.<attr>`` matching
        a registered instance lock of the enclosing class."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and self.cls is not None:
            return "{}.{}.{}".format(self.module.modname, self.cls,
                                     expr.attr)
        dotted = self.resolve_dotted(expr)
        if dotted is None:
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.local_shadow:
                return None
            return "{}.{}".format(self.module.modname, expr.id)
        return dotted

    def callable_qual_of(self, expr):
        """Project-function qualname for a callable reference: a nested
        def/lambda in this function, a module function, ``self.method``,
        or a cross-module dotted name."""
        if isinstance(expr, ast.Lambda):
            self._lambda_n += 1
            child_name = "<lambda:{}>".format(expr.lineno)
            self.nested.append(
                (expr, child_name,
                 [a.arg for a in (expr.args.posonlyargs
                                  + expr.args.args)]))
            return self._pseudo_qual(child_name)
        if isinstance(expr, ast.Name) and expr.id in self._nested_names:
            return self._nested_names[expr.id]
        dotted = self.resolve_dotted(expr)
        fi = self.project.resolve_function(self.module, dotted,
                                           cls=self.cls)
        if fi is not None:
            return fi.qualname
        return None

    # ------------------------------------------------------ statements

    def run_body(self, stmts, held):
        for stmt in stmts:
            self.run_stmt(stmt, held)

    def run_stmt(self, stmt, held):
        if isinstance(stmt, ast.Global):
            self.globals_decl.update(stmt.names)
        elif isinstance(stmt, ast.Assign):
            self.visit_expr(stmt.value, held)
            for tgt in stmt.targets:
                self._bind_target(tgt, held)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.visit_expr(stmt.value, held)
            self._bind_target(stmt.target, held)
        elif isinstance(stmt, ast.AugAssign):
            self.visit_expr(stmt.value, held)
            self._write_target(stmt.target, held)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Subscript):
                    self._write_target(tgt, held)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in stmt.items:
                lock = self.lock_id_of(item.context_expr)
                if lock is not None and self._is_known_lockish(lock,
                                                              item):
                    self.facts.acquires.append(
                        {"lock": lock, "lineno": item.context_expr.lineno,
                         "held": list(inner)})
                    inner.append(lock)
                else:
                    self.visit_expr(item.context_expr, held)
            self.run_body(stmt.body, tuple(inner))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.visit_expr(stmt.iter, held)
            self._bind_target(stmt.target, held)
            self.run_body(stmt.body, held)
            self.run_body(stmt.orelse, held)
        elif isinstance(stmt, ast.While):
            self.visit_expr(stmt.test, held)
            self.run_body(stmt.body, held)
            self.run_body(stmt.orelse, held)
        elif isinstance(stmt, ast.If):
            self.visit_expr(stmt.test, held)
            self.run_body(stmt.body, held)
            self.run_body(stmt.orelse, held)
        elif isinstance(stmt, ast.Try):
            self.run_body(stmt.body, held)
            for h in stmt.handlers:
                self.run_body(h.body, held)
            self.run_body(stmt.orelse, held)
            self.run_body(stmt.finalbody, held)
        elif isinstance(stmt, (ast.Return, ast.Expr, ast.Assert,
                               ast.Raise)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self.visit_expr(child, held)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            child_name = stmt.name
            self._nested_names[child_name] = self._pseudo_qual(child_name)
            self.local_shadow.add(child_name)
            self.nested.append(
                (stmt, child_name,
                 [a.arg for a in (stmt.args.posonlyargs
                                  + stmt.args.args)]))
        # ClassDef / imports / pass / break / continue: nothing to do.

    def _is_known_lockish(self, lock_id, item):
        """Accept a with-subject as a lock acquisition when it matches a
        registered lock OR looks like one by name ('lock'/'mutex' in the
        last segment) — cross-module instance locks are invisible to the
        registry, and treating a non-lock context manager as a lock only
        ever SUPPRESSES findings for code that is in fact serialized."""
        if lock_id in self.mf.locks:
            return True
        last = lock_id.rsplit(".", 1)[-1].lower()
        return "lock" in last or "mutex" in last

    def _bind_target(self, tgt, held):
        """Assignment target: plain names become local shadows; writes
        through subscripts/attributes on globals are global writes."""
        if isinstance(tgt, ast.Name):
            if tgt.id in self.globals_decl:
                self._record_write("{}.{}".format(self.module.modname,
                                                  tgt.id),
                                   tgt.lineno, held)
            else:
                self.local_shadow.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._bind_target(el, held)
        elif isinstance(tgt, ast.Starred):
            self._bind_target(tgt.value, held)
        elif isinstance(tgt, (ast.Subscript, ast.Attribute)):
            self._write_target(tgt, held)

    def _write_target(self, tgt, held):
        """A mutation through ``X[...] =`` / ``X.attr = `` / ``X += ``:
        a global write when the mutated container X resolves to a
        module global (bare or dotted, e.g. ``state.CACHE["x"]``), a
        param write when its base names a parameter."""
        if isinstance(tgt, ast.Name):
            gid = self.global_id_of(tgt)
            if gid is not None:
                self._record_write(gid, tgt.lineno, held)
            return
        # Peel subscripts: ``state.CACHE["x"]["y"]`` mutates the
        # container ``state.CACHE``; a top-level attribute assignment
        # ``obj.attr = v`` mutates ``obj``.
        container = tgt
        while isinstance(container, ast.Subscript):
            self.visit_expr(container.slice, held)
            container = container.value
        if container is tgt and isinstance(container, ast.Attribute):
            container = container.value
        base = container
        while isinstance(base, ast.Attribute):
            base = base.value
        if isinstance(base, ast.Name) and base.id in self.params \
                and base.id not in self.globals_decl:
            self.facts.param_writes.append(
                {"i": self.params.index(base.id), "lineno": tgt.lineno,
                 "held": list(held)})
            return
        gid = self.global_id_of(container)
        if gid is not None:
            self._record_write(gid, tgt.lineno, held)

    def _record_write(self, gid, lineno, held):
        self.facts.writes.append({"g": gid, "lineno": lineno,
                                  "held": list(held)})

    # ----------------------------------------------------- expressions

    def visit_expr(self, node, held):
        if node is None or isinstance(node, ast.Constant):
            return
        if isinstance(node, ast.Call):
            self.visit_call(node, held)
            return
        if isinstance(node, ast.Lambda):
            # A lambda not handed anywhere recognizable: still extract
            # it so its effects exist if a later pass learns the edge.
            self.callable_qual_of(node)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.comprehension)):
                self.visit_expr(child, held)
            elif isinstance(child, ast.expr_context):
                continue
        if isinstance(node, ast.comprehension):
            return
        # Subscript READS of os.environ["LDDL_TPU_X"].
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            dotted = self.resolve_dotted(node.value)
            if dotted == "os.environ":
                self._env_read(node.slice, node.lineno)

    def _env_read(self, key_node, lineno):
        key = key_node
        if isinstance(key, ast.Constant) and isinstance(key.value, str) \
                and key.value.startswith("LDDL_TPU_"):
            self.facts.env_reads.append({"name": key.value,
                                         "lineno": lineno})

    def visit_call(self, node, held):
        dotted = self.resolve_dotted(node.func)

        # Env reads: os.environ.get/setdefault, os.getenv.
        if dotted in _ENV_READ_FUNCS and node.args:
            self._env_read(node.args[0], node.lineno)

        # Spawn points.
        if dotted in _POOL_CTORS:
            self.facts.spawns.append({"kind": "pool",
                                      "lineno": node.lineno})
        elif dotted in _THREAD_CTORS or dotted in _THREAD_POOL_CTORS:
            self.facts.spawns.append({"kind": "thread",
                                      "lineno": node.lineno})

        # Thread boundary hand-offs: Thread(target=f) and .submit(f).
        if dotted in _THREAD_CTORS:
            for kw in node.keywords:
                if kw.arg == "target":
                    qual = self.callable_qual_of(kw.value)
                    if qual is not None:
                        self.facts.thread_targets.append(
                            {"target": qual, "lineno": node.lineno})
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SUBMIT_METHODS and node.args:
            qual = self.callable_qual_of(node.args[0])
            if qual is not None:
                self.facts.thread_targets.append(
                    {"target": qual, "lineno": node.lineno})

        # Signal-handler registration.
        if dotted == "signal.signal" and len(node.args) >= 2:
            qual = self.callable_qual_of(node.args[1])
            if qual is not None:
                self.facts.signal_handlers.append(
                    {"target": qual, "lineno": node.lineno})

        # Blocking operations (consumed by signal-safety).
        if dotted in _BLOCKING_FUNCS:
            self.facts.blocking.append({"what": dotted + "()",
                                        "lineno": node.lineno})
        elif dotted == "open":
            mode = None
            if len(node.args) >= 2:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if isinstance(mode, ast.Constant) \
                    and isinstance(mode.value, str) \
                    and any(c in mode.value for c in "wax+"):
                self.facts.blocking.append(
                    {"what": "write-mode open()", "lineno": node.lineno})
        elif isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr == "put" \
                    and not any(kw.arg == "timeout"
                                for kw in node.keywords) \
                    and not (len(node.args) >= 3):
                self.facts.blocking.append(
                    {"what": ".put() without timeout",
                     "lineno": node.lineno})
            elif attr == "join" and not node.args and not node.keywords:
                self.facts.blocking.append(
                    {"what": "zero-arg .join()", "lineno": node.lineno})

        # In-place mutation through a container method on a global.
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATING_METHODS:
            gid = self.global_id_of(node.func.value)
            if gid is not None:
                self._record_write(gid, node.lineno, held)

        # The call edge itself, with globals-as-arguments recorded so
        # phase B can see mutation through parameters.
        callee = None
        if isinstance(node.func, ast.Name) \
                and node.func.id in self._nested_names:
            callee = self._nested_names[node.func.id]
        else:
            fi = self.project.resolve_function(self.module, dotted,
                                               cls=self.cls)
            if fi is not None:
                callee = fi.qualname
        args_globals = {}
        for i, arg in enumerate(node.args):
            if isinstance(arg, (ast.Name, ast.Attribute)):
                gid = self.global_id_of(arg)
                if gid is not None:
                    args_globals[str(i)] = gid
        if callee is not None or args_globals:
            self.facts.calls.append(
                {"callee": callee, "dotted": dotted,
                 "lineno": node.lineno, "held": list(held),
                 "args_globals": args_globals})

        # Recurse into arguments (skip the callable we already routed
        # to a pseudo-function, so a lambda body is not double-counted
        # in the parent).
        routed = set()
        if dotted in _THREAD_CTORS:
            routed.update(id(kw.value) for kw in node.keywords
                          if kw.arg == "target")
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SUBMIT_METHODS and node.args:
            routed.add(id(node.args[0]))
        if dotted == "signal.signal" and len(node.args) >= 2:
            routed.add(id(node.args[1]))
        if isinstance(node.func, ast.Attribute):
            # The receiver itself can hold calls — e.g. the chained
            # ``threading.Thread(target=f).start()`` idiom, where the
            # spawn lives in the receiver expression.
            if not isinstance(node.func.value, (ast.Name, ast.Attribute)):
                self.visit_expr(node.func.value, held)
        elif not isinstance(node.func, ast.Name):
            self.visit_expr(node.func, held)
        for arg in node.args:
            if id(arg) not in routed:
                self.visit_expr(arg, held)
        for kw in node.keywords:
            if id(kw.value) not in routed:
                self.visit_expr(kw.value, held)


# ------------------------------------------------------------- phase B


class _Engine(object):
    """Whole-tree concurrency fixpoint over per-module facts."""

    def __init__(self, module_facts):
        self.funcs = {}  # qualname -> _CFuncFacts
        self.locks = {}  # lock id -> kind
        self.mutable_globals = {}  # gid -> (path, lineno)
        for mf in module_facts:
            for ff in mf.functions:
                self.funcs[ff.qualname] = ff
            self.locks.update(mf.locks)
            for name, info in mf.globals.items():
                if info["mutable"]:
                    gid = "{}.{}".format(mf.modname, name)
                    self.mutable_globals[gid] = (mf.path, info["lineno"])
        self.findings = []  # [(rule_id, path, lineno, message)]
        self._callers = {}  # qualname -> [(caller ff, call dict)]
        for ff in self.funcs.values():
            for call in ff.calls:
                callee = call.get("callee")
                if callee in self.funcs:
                    self._callers.setdefault(callee, []).append(
                        (ff, call))

    def emit(self, rule_id, path, lineno, message):
        self.findings.append((rule_id, path, lineno, message))

    # -------------------------------------------------- reachability

    def _closure(self, roots):
        seen = set()
        stack = [q for q in roots if q in self.funcs]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            ff = self.funcs[q]
            for call in ff.calls:
                callee = call.get("callee")
                if callee in self.funcs and callee not in seen:
                    stack.append(callee)
        return seen

    def thread_entries(self):
        """{entry qualname: (handoff path, lineno)} for every callable
        handed to a thread boundary anywhere in the tree."""
        entries = {}
        for ff in self.funcs.values():
            for t in ff.thread_targets:
                entries.setdefault(t["target"], (ff.path, t["lineno"]))
        return entries

    # ----------------------------------------------- entry-lock (must)

    def entry_locks(self, forced_empty):
        """Must-hold lock set at entry of each function: intersection
        over call sites of (locks held at the site + the caller's own
        entry set). Thread entries and signal handlers start with
        nothing held. TOP (None) for functions never called."""
        TOP = None
        entry = {}
        for q in self.funcs:
            if q in forced_empty or q not in self._callers:
                entry[q] = frozenset()
            else:
                entry[q] = TOP
        for _ in range(len(self.funcs) + 1):
            changed = False
            for q, sites in self._callers.items():
                if q in forced_empty:
                    continue
                acc = TOP
                for caller, call in sites:
                    ce = entry.get(caller.qualname)
                    if ce is TOP:
                        continue
                    site = frozenset(call["held"]) | ce
                    acc = site if acc is TOP else (acc & site)
                if acc is not TOP and acc != entry.get(q):
                    entry[q] = acc
                    changed = True
            if not changed:
                break
        return {q: (v if v is not None else frozenset())
                for q, v in entry.items()}

    # -------------------------------------------------- thread-escape

    def run_thread_escape(self):
        entries = self.thread_entries()
        reachable = self._closure(entries)
        forced = set(entries)
        for ff in self.funcs.values():
            forced.update(h["target"] for h in ff.signal_handlers)
        entry = self.entry_locks(forced)

        # Gather every write per global id: direct writes plus mutation
        # through a parameter one call level deep (global passed to a
        # helper that mutates that parameter, directly or transitively).
        deep_mut = self._deep_param_mut()
        writes = {}  # gid -> [(ff, lineno, effective held, thread side)]
        for q, ff in self.funcs.items():
            side = q in reachable
            base = entry.get(q, frozenset())
            for w in ff.writes:
                gid = w["g"]
                if gid not in self.mutable_globals:
                    continue
                eff = frozenset(w["held"]) | base
                writes.setdefault(gid, []).append(
                    (ff, w["lineno"], eff, side))
            for call in ff.calls:
                callee = call.get("callee")
                if callee not in self.funcs:
                    continue
                for i_str, gid in call["args_globals"].items():
                    if gid not in self.mutable_globals:
                        continue
                    if int(i_str) in deep_mut.get(callee, ()):
                        eff = frozenset(call["held"]) | base
                        writes.setdefault(gid, []).append(
                            (ff, call["lineno"], eff, side))

        for gid in sorted(writes):
            sites = writes[gid]
            thread_side = [s for s in sites if s[3]]
            main_side = [s for s in sites if not s[3]]
            if not thread_side or not main_side:
                continue
            def_path, def_line = self.mutable_globals[gid]
            entry_names = sorted(
                q for q in entries
                if any(s[0].qualname in self._closure([q])
                       for s in thread_side))
            via = entry_names[0] if entry_names else "?"
            for ff, lineno, eff, side in sorted(
                    sites, key=lambda s: (s[0].path, s[1])):
                if eff:
                    continue
                other = "the {} thread".format(via) if not side \
                    else "the main thread"
                self.emit(
                    "thread-escape", ff.path, lineno,
                    "mutable module global '{}' (defined {}:{}) is "
                    "written here without a recognized lock while also "
                    "written from {} (thread entry {}()); guard every "
                    "write with one shared lock or confine the state "
                    "to a single thread".format(
                        gid, def_path, def_line, other, via))

    def _deep_param_mut(self):
        """{qualname: set(param indices mutated unlocked, directly or by
        passing the param onward)} — small fixpoint."""
        mut = {}
        for q, ff in self.funcs.items():
            mut[q] = {pw["i"] for pw in ff.param_writes
                      if not pw["held"]}
        # Propagate param-to-param forwarding: ff passes its param i as
        # positional j of callee; callee mutates j => ff mutates i.
        # (args_globals only records globals, so re-scan calls is not
        # possible here without param refs — handled at extraction via
        # params being locals: a param passed on appears as a plain Name
        # arg that is NOT a global, so this stays one level deep. One
        # level catches the real tree's patterns (fleet.rotating_path,
        # series -> fleet.rotating_path) and fixtures pin it.)
        return mut

    # ----------------------------------------------------- lock-order

    def run_lock_order(self):
        # Transitive lock-acquisition closure per function.
        acq = {q: {(a["lock"], a["lineno"])
                   for a in ff.acquires}
               for q, ff in self.funcs.items()}
        for _ in range(len(self.funcs) + 1):
            changed = False
            for q, ff in self.funcs.items():
                for call in ff.calls:
                    callee = call.get("callee")
                    if callee not in self.funcs:
                        continue
                    add = {(lk, call["lineno"]) for lk, _ in acq[callee]}
                    if not add <= acq[q]:
                        acq[q] |= add
                        changed = True
            if not changed:
                break

        pairs = {}  # (outer, inner) -> (path, lineno)
        for q, ff in self.funcs.items():
            for a in ff.acquires:
                for outer in a["held"]:
                    pairs.setdefault((outer, a["lock"]),
                                     (ff.path, a["lineno"]))
            for call in ff.calls:
                callee = call.get("callee")
                if callee not in self.funcs or not call["held"]:
                    continue
                for inner, _ in acq[callee]:
                    for outer in call["held"]:
                        pairs.setdefault((outer, inner),
                                         (ff.path, call["lineno"]))

        reported = set()
        for (a, b), (path, lineno) in sorted(pairs.items(),
                                             key=lambda kv: kv[1]):
            if a == b:
                if self.locks.get(a) == "Lock":
                    self.emit(
                        "lock-order", path, lineno,
                        "non-reentrant lock '{}' acquired while already "
                        "held on this path — this deadlocks; use "
                        "threading.RLock or restructure so the lock is "
                        "taken once".format(a))
                continue
            if (b, a) in pairs and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                other_path, other_line = pairs[(b, a)]
                self.emit(
                    "lock-order", path, lineno,
                    "locks '{}' and '{}' are acquired in both orders "
                    "({} -> {} here; {} -> {} at {}:{}) — two threads "
                    "taking them concurrently deadlock; pick one global "
                    "order".format(a, b, a, b, b, a, other_path,
                                   other_line))

    # -------------------------------------------------- signal-safety

    def run_signal_safety(self):
        handlers = {}
        for ff in self.funcs.values():
            for h in ff.signal_handlers:
                handlers.setdefault(h["target"], (ff.path, h["lineno"]))
        if not handlers:
            return
        for handler in sorted(handlers):
            reg_path, reg_line = handlers[handler]
            for q in sorted(self._closure([handler])):
                ff = self.funcs[q]
                for a in ff.acquires:
                    if self.locks.get(a["lock"]) == "Lock":
                        self.emit(
                            "signal-safety", ff.path, a["lineno"],
                            "non-reentrant threading.Lock '{}' on the "
                            "signal-handler path of {}() (registered "
                            "{}:{}): a signal interrupting a frame that "
                            "holds it deadlocks the handler — use "
                            "threading.RLock".format(
                                a["lock"], handler.rsplit(".", 1)[-1],
                                reg_path, reg_line))
                if any(ff.path.startswith(p)
                       for p in SIGNAL_BLOCKING_SANCTIONED_PREFIXES):
                    continue
                for b in ff.blocking:
                    self.emit(
                        "signal-safety", ff.path, b["lineno"],
                        "blocking {} on the signal-handler path of {}() "
                        "(registered {}:{}); handlers must not block — "
                        "set a flag and do the work on the main "
                        "path".format(b["what"],
                                      handler.rsplit(".", 1)[-1],
                                      reg_path, reg_line))

    # ------------------------------------------- env-read-after-spawn

    def run_env_after_spawn(self):
        exempt = {q for q, ff in self.funcs.items()
                  if any(ff.path.startswith(p)
                         for p in ENV_SOURCE_EXEMPT_PREFIXES)}

        # Transitive summaries: does f (or anything it calls) spawn a
        # pool; does f (or anything it calls) read LDDL_TPU_* env.
        spawns = {q: any(s["kind"] == "pool" for s in ff.spawns)
                  for q, ff in self.funcs.items()}
        reads = {}
        for q, ff in self.funcs.items():
            reads[q] = set() if q in exempt else \
                {r["name"] for r in ff.env_reads}
        for _ in range(len(self.funcs) + 1):
            changed = False
            for q, ff in self.funcs.items():
                for call in ff.calls:
                    callee = call.get("callee")
                    if callee not in self.funcs:
                        continue
                    if spawns[callee] and not spawns[q]:
                        spawns[q] = True
                        changed = True
                    if q not in exempt and not reads[callee] <= reads[q]:
                        reads[q] |= reads[callee]
                        changed = True
            if not changed:
                break

        for q in sorted(self.funcs):
            ff = self.funcs[q]
            if q in exempt:
                continue
            # Spawn events visible inside this function, by line: a
            # direct pool/thread spawn, or a call into a pool-spawning
            # callee.
            spawn_events = [(s["lineno"],
                             "pool" if s["kind"] == "pool" else "thread")
                            for s in ff.spawns]
            for call in ff.calls:
                callee = call.get("callee")
                if callee in self.funcs and spawns[callee]:
                    spawn_events.append((call["lineno"], "pool"))
            if not spawn_events:
                continue
            pool_spawns = [ln for ln, kind in spawn_events
                           if kind == "pool"]
            thread_spawns = [ln for ln, kind in spawn_events
                             if kind == "thread"]

            read_events = [(r["lineno"], r["name"], None)
                           for r in ff.env_reads]
            for call in ff.calls:
                callee = call.get("callee")
                if callee in self.funcs and reads[callee]:
                    read_events.append(
                        (call["lineno"], sorted(reads[callee])[0],
                         callee))
            emitted = set()
            for lineno, name, via in sorted(read_events):
                first_pool = min((ln for ln in pool_spawns
                                  if ln < lineno), default=None)
                # Threads share the live environ: only the
                # same-function spawn-then-read window fires for them,
                # and only for DIRECT reads.
                first_thread = min((ln for ln in thread_spawns
                                    if ln < lineno), default=None) \
                    if via is None else None
                first = first_pool if first_pool is not None \
                    else first_thread
                if first is None or lineno in emitted:
                    continue
                emitted.add(lineno)
                how = "read here" if via is None else \
                    "read inside {}() called here".format(
                        via.rsplit(".", 1)[-1])
                self.emit(
                    "env-read-after-spawn", ff.path, lineno,
                    "{} {} after a worker spawn point (line {}) on the "
                    "same call path; spawned workers snapshot the "
                    "environment at spawn time, so a late read silently "
                    "desynchronizes parent and worker configuration — "
                    "read and pin it before spawning".format(
                        name, how, first))


def run_concurrency_analysis(module_facts):
    """Phase B over cached/extracted per-module concurrency facts.
    Returns ``[(rule_id, path, lineno, message)]`` BEFORE allow-list,
    suppression, and baseline filtering (core.run_check applies those,
    exactly as for the dataflow findings)."""
    eng = _Engine(module_facts)
    eng.run_thread_escape()
    eng.run_lock_order()
    eng.run_signal_safety()
    eng.run_env_after_spawn()
    # Deterministic output order; dedupe (a loop-free guarantee the
    # emitters do not individually make).
    return sorted(set(eng.findings))


# --------------------------------------------------------------- rules


class ConcurrencyRule(Rule):
    """Base for the concurrency project-scope rules: run via
    :func:`run_concurrency_analysis`, not per file."""

    scope = "project"

    def run(self, ctx):  # pragma: no cover - project rules don't run here
        return ()


@register
class ThreadEscapeRule(ConcurrencyRule):
    id = "thread-escape"
    doc = ("mutable module globals written on both sides of a thread "
           "boundary (Thread(target=), .submit() hand-offs, sink "
           "writer, LeaseKeeper, heartbeat/exporter threads) must hold "
           "a recognized lock at every write; mutation through helper "
           "parameters counts")
    # The metrics registry is the sanctioned shared-state surface: its
    # internals ARE the lock-guarded registry the rest of the tree must
    # use instead of ad-hoc module dicts.
    allow = ("lddl_tpu/observability/registry.py",)


@register
class LockOrderRule(ConcurrencyRule):
    id = "lock-order"
    doc = ("no two locks acquired in both orders across any pair of "
           "call paths (AB/BA deadlock), and no non-reentrant lock "
           "re-acquired while already held")
    allow = ()


@register
class SignalSafetyRule(ConcurrencyRule):
    id = "signal-safety"
    doc = ("signal-handler call paths must not acquire non-reentrant "
           "threading.Lock (use RLock — the PR 10 bug class) nor make "
           "blocking calls (write-mode open, queue.put without "
           "timeout, zero-arg .join(), time.sleep); observability's "
           "flush-on-TERM writes are sanctioned at the engine level")
    allow = ()


@register
class EnvReadAfterSpawnRule(ConcurrencyRule):
    id = "env-read-after-spawn"
    doc = ("no LDDL_TPU_* env reads after a process-pool spawn point "
           "on the same call path — workers snapshot the env at spawn, "
           "so late reads desynchronize parent/worker config; "
           "observability/faults gating reads are exempt sources")
    allow = ()


CONCURRENCY_RULE_IDS = ("thread-escape", "lock-order", "signal-safety",
                        "env-read-after-spawn")
