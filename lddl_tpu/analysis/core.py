"""AST-based static-analysis framework for the pipeline's invariants.

The pipeline's load-bearing guarantees (rank-identical bin choice from the
shared seeded RNG streams in ``utils/rng.py``, byte-identical resume,
atomic-only publish into shard directories — SURVEY §0) used to be enforced
by two grep-style lint tests plus reviewer vigilance. This package turns
them into machine-checked rules that run over the whole source tree on
every test run (``tests/test_analysis.py``) and from the CLI
(``python -m tools.lddl_check``).

Framework pieces:

- :class:`Rule` — an AST visitor with an id, a docstring explaining what it
  protects, optional ``allow`` (fnmatch patterns of repo-relative paths the
  rule never fires on) and ``only`` (patterns it is restricted to).
- registry — rules self-register via :func:`register`; :func:`get_rules`
  resolves an optional name filter.
- suppressions — ``# lddl: disable=<rule>[,<rule>...]`` on the flagged
  line, or on a comment-only line directly above it, silences a finding.
  Every suppression should carry a justification in the surrounding
  comment; they are grep-able so reviewers can audit the full set.
- baseline — a checked-in JSON file of grandfathered findings (each with a
  one-line ``reason``). A finding matches a baseline entry on
  ``(rule, path, stripped source line)`` so entries survive unrelated line
  drift. ``lddl_check`` exits nonzero only on NEW findings.
- output — human-readable ``path:line: [rule] message`` lines or ``--json``
  for machine consumption (the CI test parses it).
"""

import ast
import fnmatch
import json
import os
import re

# Repo root = dirname of the package that contains lddl_tpu/.
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join("tools", "lddl_check_baseline.json")

# The directive may sit anywhere inside a comment ("# why ... lddl:
# disable=x"), but must be after a '#' so string literals never suppress.
_SUPPRESS_RE = re.compile(r"#.*?lddl:\s*disable=([A-Za-z0-9_\-, ]+)")


class Finding(object):
    """One rule violation at one source location."""

    __slots__ = ("rule", "path", "line", "col", "message", "snippet")

    def __init__(self, rule, path, line, col, message, snippet=""):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.snippet = snippet

    def key(self):
        """Baseline identity: stable under unrelated line-number drift."""
        return (self.rule, self.path, self.snippet)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet}

    def format(self):
        return "{}:{}: [{}] {}".format(self.path, self.line, self.rule,
                                       self.message)

    def __repr__(self):
        return "Finding({})".format(self.format())


class Context(object):
    """Everything a rule needs about one source file: the parsed tree, a
    parent map (child AST node -> parent), the raw lines, and an
    import-alias resolver so ``np.random.default_rng`` and
    ``numpy.random.default_rng`` normalize to one dotted name."""

    def __init__(self, path, source, tree):
        self.path = path  # repo-relative, posix separators
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.aliases = _import_aliases(tree)

    def snippet_at(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule_id, node, message):
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule_id, self.path, line, col, message,
                       self.snippet_at(line))

    def resolve_call(self, node):
        """Normalized dotted name of a Call's callee, or None.

        Only pure ``Name(.Attribute)*`` chains resolve; the head segment is
        mapped through the module's import aliases (``import numpy as np``
        makes ``np.random.seed`` -> ``numpy.random.seed``; ``from datetime
        import datetime`` makes ``datetime.now`` -> ``datetime.datetime.now``).
        """
        return self.resolve_name(node.func)

    def resolve_name(self, node):
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])

    def ancestors(self, node):
        while node in self.parents:
            node = self.parents[node]
            yield node


def _import_aliases(tree):
    """{local name: canonical dotted module/attr} from top-level-ish
    imports anywhere in the tree (function-local imports included —
    this codebase lazy-imports jax deliberately)."""
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                aliases[local] = a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            # Relative imports keep just the module path ("..resilience.io"
            # -> "resilience.io"): rules match on suffixes of package-local
            # names, absolute prefixes on external ones.
            mod = (node.module or "").lstrip(".")
            for a in node.names:
                if a.name == "*":
                    continue
                local = a.asname or a.name
                aliases[local] = "{}.{}".format(mod, a.name) if mod else a.name
    return aliases


class Rule(object):
    """Base class: subclasses set ``id``, ``doc`` (what the rule protects,
    one line — surfaced by ``lddl_check --list-rules`` and the README
    table) and implement :meth:`run` yielding Findings."""

    id = None
    doc = ""
    # fnmatch patterns (repo-relative posix paths) the rule never fires on.
    allow = ()
    # If set, the rule only runs on files matching one of these patterns.
    only = None

    def applies_to(self, path):
        if self.only is not None and not _match_any(path, self.only):
            return False
        return not _match_any(path, self.allow)

    def run(self, ctx):
        raise NotImplementedError


def _match_any(path, patterns):
    return any(fnmatch.fnmatch(path, pat) for pat in patterns)


_REGISTRY = []


def register(cls):
    """Class decorator: add a Rule subclass to the global registry."""
    assert cls.id, "rule must define an id"
    assert all(r.id != cls.id for r in _REGISTRY), \
        "duplicate rule id {}".format(cls.id)
    _REGISTRY.append(cls())
    return cls


def all_rules():
    return list(_REGISTRY)


def get_rules(names=None):
    """Resolve a rule-name filter (iterable of ids, or None for all)."""
    if names is None:
        return all_rules()
    names = set(names)
    unknown = names - {r.id for r in _REGISTRY}
    if unknown:
        raise ValueError("unknown rule id(s): {}; known: {}".format(
            sorted(unknown), sorted(r.id for r in _REGISTRY)))
    return [r for r in _REGISTRY if r.id in names]


def suppressions(lines):
    """{lineno: set(rule ids)} from ``# lddl: disable=...`` comments. A
    directive on a code line covers that line; a directive on a
    comment-only line covers the next line as well."""
    supp = {}
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        ids = {part.strip() for part in m.group(1).split(",") if part.strip()}
        supp.setdefault(i, set()).update(ids)
        if text.lstrip().startswith("#"):
            supp.setdefault(i + 1, set()).update(ids)
    return supp


def analyze_source(source, path, rules=None):
    """Run ``rules`` over one in-memory source file.

    ``path`` is the repo-relative posix path the rules see (allow/only
    lists match against it). Returns (findings, suppressed) — findings
    survive suppression comments; suppressed did not."""
    rules = all_rules() if rules is None else rules
    tree = ast.parse(source, filename=path)
    ctx = Context(path, source, tree)
    supp = suppressions(ctx.lines)
    findings, suppressed = [], []
    for rule in rules:
        if not rule.applies_to(path):
            continue
        for f in rule.run(ctx):
            if f.rule in supp.get(f.line, ()):
                suppressed.append(f)
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, suppressed


def iter_python_files(paths, root=None):
    """Yield (abs path, repo-relative posix path) for every .py under
    ``paths`` (files or directories), in sorted order — the walk itself
    must not leak filesystem order."""
    root = root or REPO_ROOT
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(ap):
            # A typo'd path must not make the gate silently green.
            raise FileNotFoundError(
                "lddl-check path does not exist: {}".format(ap))
        if os.path.isfile(ap):
            yield ap, _relpath(ap, root)
            continue
        # Deterministic walk: dirnames sorted in place, filenames sorted
        # below — the FS order never escapes this loop.
        for dirpath, dirnames, filenames in os.walk(ap):  # lddl: disable=unsorted-iteration
            dirnames.sort()
            for name in sorted(filenames):
                if name.endswith(".py"):
                    fp = os.path.join(dirpath, name)
                    yield fp, _relpath(fp, root)


def _relpath(path, root):
    rel = os.path.relpath(os.path.abspath(path), root)
    return rel.replace(os.sep, "/")


def load_baseline(path):
    """[{rule, path, match, reason}, ...] from the baseline JSON (absent
    file reads as empty)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return []
    entries = data.get("entries", []) if isinstance(data, dict) else data
    return [e for e in entries if isinstance(e, dict)]


def baseline_entry(finding, reason=""):
    return {"rule": finding.rule, "path": finding.path,
            "match": finding.snippet, "reason": reason}


def split_baselined(findings, entries):
    """Partition findings into (new, baselined) against baseline entries.
    Each entry absorbs any number of findings with the same
    (rule, path, stripped-line) identity."""
    keys = {(e.get("rule"), e.get("path"), e.get("match")) for e in entries}
    new, old = [], []
    for f in findings:
        (old if f.key() in keys else new).append(f)
    return new, old


class Report(object):
    """Result of a tree-wide run: new findings, baselined findings,
    inline-suppressed findings, parse errors, files analyzed."""

    def __init__(self):
        self.new = []
        self.baselined = []
        self.suppressed = []
        self.errors = []  # (path, message)
        self.files = 0

    @property
    def ok(self):
        return not self.new and not self.errors

    def to_dict(self):
        return {
            "ok": self.ok,
            "files": self.files,
            "findings": [f.to_dict() for f in self.new],
            "baselined": [f.to_dict() for f in self.baselined],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "errors": [{"path": p, "message": m} for p, m in self.errors],
        }


def run_check(paths, rules=None, baseline_path=None, root=None):
    """Analyze every .py under ``paths`` and return a :class:`Report`.

    ``baseline_path`` defaults to the checked-in
    ``tools/lddl_check_baseline.json`` (pass ``baseline_path=""`` to run
    without a baseline)."""
    root = root or REPO_ROOT
    rules = all_rules() if rules is None else rules
    if baseline_path is None:
        baseline_path = os.path.join(root, DEFAULT_BASELINE)
    entries = load_baseline(baseline_path) if baseline_path else []
    report = Report()
    for abspath, relpath in iter_python_files(paths, root=root):
        report.files += 1
        try:
            with open(abspath, "r", encoding="utf-8") as f:
                source = f.read()
            findings, suppressed = analyze_source(source, relpath, rules)
        except SyntaxError as e:
            report.errors.append((relpath, "syntax error: {}".format(e)))
            continue
        report.suppressed.extend(suppressed)
        new, old = split_baselined(findings, entries)
        report.new.extend(new)
        report.baselined.extend(old)
    return report
