"""AST-based static-analysis framework for the pipeline's invariants.

The pipeline's load-bearing guarantees (rank-identical bin choice from the
shared seeded RNG streams in ``utils/rng.py``, byte-identical resume,
atomic-only publish into shard directories — SURVEY §0) used to be enforced
by two grep-style lint tests plus reviewer vigilance. This package turns
them into machine-checked rules that run over the whole source tree on
every test run (``tests/test_analysis.py``) and from the CLI
(``python -m tools.lddl_check``).

Framework pieces:

- :class:`Rule` — an AST visitor with an id, a docstring explaining what it
  protects, optional ``allow`` (fnmatch patterns of repo-relative paths the
  rule never fires on) and ``only`` (patterns it is restricted to).
- registry — rules self-register via :func:`register`; :func:`get_rules`
  resolves an optional name filter.
- suppressions — ``# lddl: disable=<rule>[,<rule>...]`` on the flagged
  line, or on a comment-only line directly above it, silences a finding.
  Every suppression should carry a justification in the surrounding
  comment; they are grep-able so reviewers can audit the full set.
- baseline — a checked-in JSON file of grandfathered findings (each with a
  one-line ``reason``). A finding matches a baseline entry on
  ``(rule, path, stripped source line)`` so entries survive unrelated line
  drift. ``lddl_check`` exits nonzero only on NEW findings.
- output — human-readable ``path:line: [rule] message`` lines or ``--json``
  for machine consumption (the CI test parses it).
"""

import ast
import fnmatch
import hashlib
import json
import os
import re
import time

# Repo root = dirname of the package that contains lddl_tpu/.
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join("tools", "lddl_check_baseline.json")

# Repo-relative default location of the AST+summary cache (content-hash
# keyed; see _Cache). Safe to delete at any time.
DEFAULT_CACHE = ".lddl_check_cache.json"

# Bump to invalidate every cache entry when rule/engine semantics change.
ANALYSIS_VERSION = 1

# The directive may sit anywhere inside a comment ("# why ... lddl:
# disable=x"), but must be after a '#' so string literals never suppress.
_SUPPRESS_RE = re.compile(r"#.*?lddl:\s*disable=([A-Za-z0-9_\-, ]+)")


class Finding(object):
    """One rule violation at one source location."""

    __slots__ = ("rule", "path", "line", "col", "message", "snippet")

    def __init__(self, rule, path, line, col, message, snippet=""):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.snippet = snippet

    def key(self):
        """Baseline identity: stable under unrelated line-number drift."""
        return (self.rule, self.path, self.snippet)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet}

    def format(self):
        return "{}:{}: [{}] {}".format(self.path, self.line, self.rule,
                                       self.message)

    def __repr__(self):
        return "Finding({})".format(self.format())


class Context(object):
    """Everything a rule needs about one source file: the parsed tree, a
    parent map (child AST node -> parent), the raw lines, and an
    import-alias resolver so ``np.random.default_rng`` and
    ``numpy.random.default_rng`` normalize to one dotted name."""

    def __init__(self, path, source, tree):
        self.path = path  # repo-relative, posix separators
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.aliases = _import_aliases(tree)

    def snippet_at(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule_id, node, message):
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule_id, self.path, line, col, message,
                       self.snippet_at(line))

    def resolve_call(self, node):
        """Normalized dotted name of a Call's callee, or None.

        Only pure ``Name(.Attribute)*`` chains resolve; the head segment is
        mapped through the module's import aliases (``import numpy as np``
        makes ``np.random.seed`` -> ``numpy.random.seed``; ``from datetime
        import datetime`` makes ``datetime.now`` -> ``datetime.datetime.now``).
        """
        return self.resolve_name(node.func)

    def resolve_name(self, node):
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])

    def ancestors(self, node):
        while node in self.parents:
            node = self.parents[node]
            yield node


def _import_aliases(tree):
    """{local name: canonical dotted module/attr} from top-level-ish
    imports anywhere in the tree (function-local imports included —
    this codebase lazy-imports jax deliberately)."""
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                aliases[local] = a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            # Relative imports keep just the module path ("..resilience.io"
            # -> "resilience.io"): rules match on suffixes of package-local
            # names, absolute prefixes on external ones.
            mod = (node.module or "").lstrip(".")
            for a in node.names:
                if a.name == "*":
                    continue
                local = a.asname or a.name
                aliases[local] = "{}.{}".format(mod, a.name) if mod else a.name
    return aliases


class Rule(object):
    """Base class: subclasses set ``id``, ``doc`` (what the rule protects,
    one line — surfaced by ``lddl_check --list-rules`` and the README
    table) and implement :meth:`run` yielding Findings."""

    id = None
    doc = ""
    # fnmatch patterns (repo-relative posix paths) the rule never fires on.
    allow = ()
    # If set, the rule only runs on files matching one of these patterns.
    only = None
    # "file" rules run per file via :meth:`run`; "project" rules are fed
    # by the whole-tree dataflow engine (see flow_rules.py) and use
    # allow/only purely as finding-path filters.
    scope = "file"

    def applies_to(self, path):
        if self.only is not None and not _match_any(path, self.only):
            return False
        return not _match_any(path, self.allow)

    def run(self, ctx):
        raise NotImplementedError


def _match_any(path, patterns):
    return any(fnmatch.fnmatch(path, pat) for pat in patterns)


_REGISTRY = []


def register(cls):
    """Class decorator: add a Rule subclass to the global registry."""
    assert cls.id, "rule must define an id"
    assert all(r.id != cls.id for r in _REGISTRY), \
        "duplicate rule id {}".format(cls.id)
    _REGISTRY.append(cls())
    return cls


def all_rules():
    return list(_REGISTRY)


def get_rules(names=None):
    """Resolve a rule-name filter (iterable of ids, or None for all)."""
    if names is None:
        return all_rules()
    names = set(names)
    unknown = names - {r.id for r in _REGISTRY}
    if unknown:
        raise ValueError("unknown rule id(s): {}; known: {}".format(
            sorted(unknown), sorted(r.id for r in _REGISTRY)))
    return [r for r in _REGISTRY if r.id in names]


def suppressions(lines):
    """{lineno: set(rule ids)} from ``# lddl: disable=...`` comments. A
    directive on a code line covers that line; a directive on a
    comment-only line covers the next line as well."""
    supp = {}
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        ids = {part.strip() for part in m.group(1).split(",") if part.strip()}
        supp.setdefault(i, set()).update(ids)
        if text.lstrip().startswith("#"):
            supp.setdefault(i + 1, set()).update(ids)
    return supp


def analyze_source(source, path, rules=None):
    """Run ``rules`` over one in-memory source file.

    ``path`` is the repo-relative posix path the rules see (allow/only
    lists match against it). Returns (findings, suppressed) — findings
    survive suppression comments; suppressed did not."""
    rules = all_rules() if rules is None else rules
    tree = ast.parse(source, filename=path)
    ctx = Context(path, source, tree)
    supp = suppressions(ctx.lines)
    findings, suppressed = [], []
    for rule in rules:
        if not rule.applies_to(path):
            continue
        for f in rule.run(ctx):
            if f.rule in supp.get(f.line, ()):
                suppressed.append(f)
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, suppressed


def iter_python_files(paths, root=None):
    """Yield (abs path, repo-relative posix path) for every .py under
    ``paths`` (files or directories), in sorted order — the walk itself
    must not leak filesystem order."""
    root = root or REPO_ROOT
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(ap):
            # A typo'd path must not make the gate silently green.
            raise FileNotFoundError(
                "lddl-check path does not exist: {}".format(ap))
        if os.path.isfile(ap):
            yield ap, _relpath(ap, root)
            continue
        # Deterministic walk: dirnames sorted in place, filenames sorted
        # below — the FS order never escapes this loop.
        for dirpath, dirnames, filenames in os.walk(ap):  # lddl: disable=unsorted-iteration
            dirnames.sort()
            for name in sorted(filenames):
                if name.endswith(".py"):
                    fp = os.path.join(dirpath, name)
                    yield fp, _relpath(fp, root)


def _relpath(path, root):
    rel = os.path.relpath(os.path.abspath(path), root)
    return rel.replace(os.sep, "/")


def load_baseline(path):
    """[{rule, path, match, reason}, ...] from the baseline JSON (absent
    file reads as empty)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return []
    entries = data.get("entries", []) if isinstance(data, dict) else data
    return [e for e in entries if isinstance(e, dict)]


def baseline_entry(finding, reason="", count=1):
    entry = {"rule": finding.rule, "path": finding.path,
             "match": finding.snippet, "reason": reason}
    if count != 1:
        entry["count"] = count
    return entry


def split_baselined(findings, entries):
    """Partition findings into (new, baselined) against baseline entries.

    Matching is COUNT-aware: an entry absorbs ``count`` findings
    (default 1) with the same (rule, path, stripped-line) identity, so
    pasting a second copy of a baselined line into the same file is a new
    finding, not a free ride on the first copy's grandfathering."""
    remaining = {}
    for e in entries:
        key = (e.get("rule"), e.get("path"), e.get("match"))
        remaining[key] = remaining.get(key, 0) + int(e.get("count", 1))
    new, old = [], []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if remaining.get(f.key(), 0) > 0:
            remaining[f.key()] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


class Report(object):
    """Result of a tree-wide run: new findings, baselined findings,
    inline-suppressed findings, parse errors, files analyzed, and cache/
    timing stats."""

    def __init__(self):
        self.new = []
        self.baselined = []
        self.suppressed = []
        self.errors = []  # (path, message)
        self.files = 0
        self.files_cached = 0  # served from the content-hash cache
        self.elapsed_s = 0.0

    @property
    def ok(self):
        return not self.new and not self.errors

    def to_dict(self):
        return {
            "ok": self.ok,
            "files": self.files,
            "files_cached": self.files_cached,
            "elapsed_s": round(self.elapsed_s, 3),
            "findings": [f.to_dict() for f in self.new],
            "baselined": [f.to_dict() for f in self.baselined],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "errors": [{"path": p, "message": m} for p, m in self.errors],
        }


class _Cache(object):
    """Content-hash keyed per-file cache of parse + analysis artifacts.

    Each entry stores, for one (file content, ANALYSIS_VERSION, rule-set)
    state: the full-rule-set syntactic findings and suppressions, the
    suppression-comment map, the module's dataflow facts (phase A of
    :mod:`.dataflow`), and a resolution stub (functions + import aliases)
    so the project model can be rebuilt WITHOUT re-parsing cache hits.
    The interprocedural fixpoint (phase B) is always recomputed — it is
    cheap, and it is how an edit in one file updates findings in its
    callers and callees."""

    def __init__(self, path, rule_sig):
        self.path = path
        self.rule_sig = rule_sig
        self.entries = {}
        self.dirty = False
        if path and os.path.isfile(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    data = json.load(f)
                if (data.get("version") == ANALYSIS_VERSION
                        and data.get("rules") == rule_sig):
                    self.entries = data.get("files", {})
            except (OSError, ValueError):
                # A corrupt/unreadable cache reads as empty: every file
                # re-analyzes and the next save rewrites it.
                self.entries = {}

    def get(self, relpath, content_hash):
        entry = self.entries.get(relpath)
        if entry is not None and entry.get("hash") == content_hash:
            return entry
        return None

    def put(self, relpath, entry):
        self.entries[relpath] = entry
        self.dirty = True

    def save(self):
        if not self.path or not self.dirty:
            return
        try:
            with open(self.path, "w", encoding="utf-8") as f:
                json.dump({"version": ANALYSIS_VERSION,
                           "rules": self.rule_sig,
                           "files": self.entries}, f)
        # Best-effort accelerator: an unwritable cache (read-only
        # checkout, full disk) must never fail the check itself.
        except OSError:  # lddl: disable=swallowed-error
            pass


def _sha256(text):
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


_SELF_DIGEST = None


def _rule_signature():
    """Cache key component: registered rule ids PLUS a digest of the
    analysis package's own sources, so editing a rule or the engine
    invalidates every cached entry without a manual ANALYSIS_VERSION
    bump. Entries cache the FULL rule set's results (``--rules`` filters
    at report time), so the signature ignores any per-run filter."""
    global _SELF_DIGEST
    if _SELF_DIGEST is None:
        h = hashlib.sha256()
        pkg_dir = os.path.dirname(os.path.abspath(__file__))
        for name in sorted(os.listdir(pkg_dir)):
            if name.endswith(".py"):
                with open(os.path.join(pkg_dir, name), "rb") as f:
                    h.update(name.encode())
                    h.update(f.read())
        _SELF_DIGEST = h.hexdigest()
    return sorted(r.id for r in _REGISTRY) + [_SELF_DIGEST]


def run_check(paths, rules=None, baseline_path=None, root=None,
              cache_path=None, report_paths=None):
    """Analyze every .py under ``paths`` and return a :class:`Report`.

    - ``baseline_path`` defaults to the checked-in
      ``tools/lddl_check_baseline.json`` (pass ``""`` to disable).
    - ``cache_path``: AST+summary cache file. ``None`` disables caching;
      the CLI passes ``<root>/.lddl_check_cache.json`` by default.
    - ``report_paths``: optional iterable of repo-relative paths —
      findings are REPORTED only for these files while the analysis (and
      the interprocedural fixpoint) still covers all of ``paths``. This
      is the ``--changed-only`` fast path.
    """
    from . import concurrency as _conc
    from . import flow_rules as _flow
    from . import dataflow as _dataflow
    from . import project as _project

    t0 = time.monotonic()
    root = root or REPO_ROOT
    rules = all_rules() if rules is None else rules
    selected_ids = {r.id for r in rules}
    file_rules = [r for r in all_rules() if r.scope == "file"]
    flow_rules_by_id = {r.id: r for r in all_rules()
                        if r.scope == "project"}
    want_flow = any(r.scope == "project" for r in rules)
    if baseline_path is None:
        baseline_path = os.path.join(root, DEFAULT_BASELINE)
    entries = load_baseline(baseline_path) if baseline_path else []
    report = Report()
    # The analyzed path set is part of the cache signature: facts
    # extracted under a PARTIAL project model (an explicit-path run)
    # record unresolvable cross-package calls as opaque externals, and
    # reusing them in a full-tree run would silently drop flow findings.
    cache = _Cache(cache_path,
                   _rule_signature() + [sorted(str(p) for p in paths)])

    proj = _project.Project()
    parsed_modules = []  # ModuleInfo needing fact extraction
    module_facts = []  # dataflow._ModuleFacts for every healthy file
    conc_facts = []  # concurrency._CModuleFacts for every healthy file
    per_file = {}  # relpath -> {"supp": {...}, "lines": [...]}
    findings = []  # pre-baseline, post-suppression
    cache_entries_pending = {}  # relpath -> entry missing "facts"

    seen_paths = set()
    for abspath, relpath in iter_python_files(paths, root=root):
        if relpath in seen_paths:
            # Overlapping path arguments (e.g. "lddl_tpu
            # lddl_tpu/preprocess") must not analyze a file twice: the
            # count-aware baseline would see the duplicate findings as
            # NEW.
            continue
        seen_paths.add(relpath)
        report.files += 1
        try:
            with open(abspath, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            report.errors.append((relpath, "unreadable: {}".format(e)))
            continue
        content_hash = _sha256(source)
        lines = source.splitlines()
        hit = cache.get(relpath, content_hash)
        if hit is not None:
            report.files_cached += 1
            supp = {int(k): set(v) for k, v in hit["supp"].items()}
            per_file[relpath] = {"supp": supp, "lines": lines}
            for d in hit["findings"]:
                f = Finding(d["rule"], d["path"], d["line"], d["col"],
                            d["message"], d["snippet"])
                if f.rule in selected_ids:
                    findings.append(f)
            for d in hit["suppressed"]:
                f = Finding(d["rule"], d["path"], d["line"], d["col"],
                            d["message"], d["snippet"])
                if f.rule in selected_ids:
                    report.suppressed.append(f)
            module_facts.append(
                _dataflow._ModuleFacts.from_dict(hit["facts"]))
            conc_facts.append(
                _conc._CModuleFacts.from_dict(hit["cfacts"]))
            _add_stub_module(proj, relpath, hit["stub"])
            continue
        try:
            tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:
            report.errors.append((relpath, "syntax error: {}".format(e)))
            continue
        ctx = Context(relpath, source, tree)
        supp = suppressions(ctx.lines)
        per_file[relpath] = {"supp": supp, "lines": lines}
        raw, kept, supped = [], [], []
        for rule in file_rules:
            if not rule.applies_to(relpath):
                continue
            for f in rule.run(ctx):
                raw.append(f)
        for f in raw:
            (supped if f.rule in supp.get(f.line, ()) else kept).append(f)
        findings.extend(f for f in kept if f.rule in selected_ids)
        report.suppressed.extend(f for f in supped
                                 if f.rule in selected_ids)
        mod = proj.add_source(relpath, source, tree=tree)
        parsed_modules.append(mod)
        cache_entries_pending[relpath] = {
            "hash": content_hash,
            "findings": [f.to_dict() for f in kept],
            "suppressed": [f.to_dict() for f in supped],
            "supp": {str(k): sorted(v) for k, v in supp.items()},
            "stub": _stub_of_module(mod),
        }

    # Phase A for newly-parsed files (needs the COMPLETE project model so
    # cross-module calls resolve), then cache them.
    for mod in parsed_modules:
        mf = _dataflow.extract_module_facts(proj, mod)
        module_facts.append(mf)
        cf = _conc.extract_module_facts(proj, mod)
        conc_facts.append(cf)
        entry = cache_entries_pending[mod.path]
        entry["facts"] = mf.to_dict()
        entry["cfacts"] = cf.to_dict()
        cache.put(mod.path, entry)

    # Phase B: the interprocedural fixpoint + flow findings. The
    # concurrency findings chain through the same routing so allow
    # lists, suppressions, ``--rules`` filters, and the baseline apply
    # identically.
    raw_flow = []
    if want_flow and module_facts:
        raw_flow.extend(_flow.run_flow_analysis(module_facts))
    if want_flow and conc_facts:
        raw_flow.extend(_conc.run_concurrency_analysis(conc_facts))
    if raw_flow:
        for rule_id, path, lineno, message in raw_flow:
            rule = flow_rules_by_id.get(rule_id)
            if rule is None or rule_id not in selected_ids:
                continue
            if not rule.applies_to(path):
                continue
            info = per_file.get(path)
            snippet = ""
            if info and 1 <= lineno <= len(info["lines"]):
                snippet = info["lines"][lineno - 1].strip()
            f = Finding(rule_id, path, lineno, 0, message, snippet)
            if info and rule_id in info["supp"].get(lineno, ()):
                report.suppressed.append(f)
            else:
                findings.append(f)

    if report_paths is not None:
        wanted = set(report_paths)
        findings = [f for f in findings if f.path in wanted]
        report.suppressed = [f for f in report.suppressed
                             if f.path in wanted]

    new, old = split_baselined(findings, entries)
    report.new.extend(new)
    report.baselined.extend(old)
    report.new.sort(key=lambda f: (f.path, f.line, f.rule))
    report.baselined.sort(key=lambda f: (f.path, f.line, f.rule))
    cache.save()
    report.elapsed_s = time.monotonic() - t0
    return report


def _stub_of_module(mod):
    """Resolution-only snapshot of a parsed module for the cache: enough
    for OTHER files' call sites to resolve into it without re-parsing."""
    return {
        "modname": mod.modname,
        "aliases": mod.aliases,
        "functions": [
            {"local": local, "name": fi.name, "cls": fi.cls,
             "params": fi.params, "lineno": fi.lineno}
            for local, fi in sorted(mod.functions.items())
        ],
    }


def _add_stub_module(proj, relpath, stub):
    from .project import FunctionInfo, ModuleInfo

    mod = ModuleInfo.__new__(ModuleInfo)
    mod.path = relpath
    mod.source = ""
    mod.lines = []
    mod.tree = None
    mod.modname = stub["modname"]
    mod.aliases = dict(stub["aliases"])
    mod.functions = {}
    mod.global_assigns = {}
    for fd in stub["functions"]:
        qual = "{}.{}".format(mod.modname, fd["local"])
        fi = FunctionInfo.__new__(FunctionInfo)
        fi.qualname = qual
        fi.name = fd["name"]
        fi.cls = fd["cls"]
        fi.module = mod
        fi.path = relpath
        fi.node = None
        fi.lineno = fd["lineno"]
        fi.params = list(fd["params"])
        mod.functions[fd["local"]] = fi
    proj.modules_by_path[relpath] = mod
    proj.modules_by_name[mod.modname] = mod
    for fi in mod.functions.values():
        proj.functions[fi.qualname] = fi
    return mod
