"""Whole-program project model for the flow rules.

The syntactic rules in :mod:`.rules` see one file at a time, which means a
one-line helper (``def now(): return time.time()``) launders any guarded
pattern past them. The flow rules instead consult this model: every
analyzed file parsed once, import/name bindings resolved to
fully-qualified dotted names, functions and methods indexed, and
re-export chains (``from .tracing import span`` in a package
``__init__``) followed — so a call site anywhere in the tree resolves to
the :class:`FunctionInfo` that actually runs.

Scope and precision (deliberate):

- Name resolution is purely static: ``Name(.Attribute)*`` chains through
  import aliases, module-local definitions, and ``self.method`` within a
  class. Values passed around as first-class functions, dynamic
  attributes, and subclass dispatch do not resolve (the taint engine
  treats those calls as opaque and over-approximates their data flow).
- A module's top-level simple assignments are recorded so module-global
  state (``_jitter_rng = random.Random()``) participates in the taint
  analysis.
"""

import ast


def module_name_of(relpath):
    """Dotted module name for a repo-relative posix path.

    ``lddl_tpu/preprocess/runner.py -> lddl_tpu.preprocess.runner``;
    package ``__init__.py`` maps to the package itself.
    """
    parts = relpath[:-3].split("/") if relpath.endswith(".py") \
        else relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class FunctionInfo(object):
    """One function or method definition in the project."""

    __slots__ = ("qualname", "name", "cls", "module", "path", "node",
                 "params", "lineno")

    def __init__(self, qualname, name, cls, module, path, node):
        self.qualname = qualname  # e.g. lddl_tpu.utils.fs.mkdir
        self.name = name
        self.cls = cls  # enclosing class name or None
        self.module = module  # ModuleInfo
        self.path = path
        self.node = node
        self.lineno = node.lineno
        self.params = [a.arg for a in (node.args.posonlyargs
                                       + node.args.args)]

    def __repr__(self):
        return "FunctionInfo({})".format(self.qualname)


class ModuleInfo(object):
    """One parsed source file: tree, aliases, functions, globals."""

    def __init__(self, path, source, tree):
        self.path = path  # repo-relative posix
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.modname = module_name_of(path)
        self.aliases = _resolve_aliases(tree, self.modname,
                                        path.endswith("__init__.py"))
        self.functions = {}  # "f" or "Cls.m" -> FunctionInfo
        self.global_assigns = {}  # name -> ast expr (last simple assign)
        self._index()

    def _index(self):
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(node, cls=None)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._add_function(item, cls=node.name)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.global_assigns[tgt.id] = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Name):
                self.global_assigns[node.target.id] = node.value

    def _add_function(self, node, cls):
        local = "{}.{}".format(cls, node.name) if cls else node.name
        qual = "{}.{}".format(self.modname, local)
        self.functions[local] = FunctionInfo(qual, node.name, cls, self,
                                             self.path, node)


def _resolve_aliases(tree, modname, is_package):
    """Like :func:`core._import_aliases` but with relative imports made
    absolute against the importing module's package, so
    ``from ..resilience import io`` inside ``lddl_tpu.preprocess.runner``
    binds ``io -> lddl_tpu.resilience.io`` (not the bare ``resilience.io``
    the per-file rules match on suffixes of)."""
    pkg_parts = modname.split(".") if is_package \
        else modname.split(".")[:-1]
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    aliases[head] = head
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                anchor = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                base = anchor + (node.module.split(".") if node.module
                                 else [])
            else:
                base = (node.module or "").split(".") if node.module else []
            for a in node.names:
                if a.name == "*":
                    continue
                local = a.asname or a.name
                aliases[local] = ".".join(base + [a.name]) if base \
                    else a.name
    return aliases


class Project(object):
    """All analyzed modules plus cross-module name resolution."""

    def __init__(self):
        self.modules_by_path = {}
        self.modules_by_name = {}
        self.functions = {}  # fully-qualified qualname -> FunctionInfo

    def add_source(self, path, source, tree=None):
        tree = tree if tree is not None else ast.parse(source,
                                                       filename=path)
        mod = ModuleInfo(path, source, tree)
        self.modules_by_path[path] = mod
        self.modules_by_name[mod.modname] = mod
        for fi in mod.functions.values():
            self.functions[fi.qualname] = fi
        return mod

    # ------------------------------------------------------- resolution

    def resolve_dotted(self, module, dotted_node):
        """Absolute dotted name of a ``Name(.Attribute)*`` chain seen in
        ``module``, or None for anything dynamic. Head segment maps
        through the module's import aliases."""
        parts = []
        node = dotted_node
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = module.aliases.get(parts[0], parts[0])
        return ".".join(head.split(".") + parts[1:])

    def resolve_function(self, module, absolute, cls=None, _seen=None):
        """:class:`FunctionInfo` for an absolute dotted name, following
        re-export chains; None when the name is not a project function.

        ``cls`` names the class whose method body the lookup happens in,
        so ``self.helper`` resolves to ``module.Cls.helper``.
        """
        if absolute is None:
            return None
        _seen = _seen if _seen is not None else set()
        if absolute in _seen:
            return None
        _seen.add(absolute)

        parts = absolute.split(".")
        # self.method() inside a class body.
        if parts[0] == "self" and cls is not None and len(parts) == 2:
            return module.functions.get("{}.{}".format(cls, parts[1]))
        # Module-local: bare f() / Cls.m reference.
        if len(parts) <= 2:
            local = ".".join(parts)
            if local in module.functions:
                return module.functions[local]

        fi = self.functions.get(absolute)
        if fi is not None:
            return fi
        # <module>.<attr> where <module> is a project module: the attr may
        # itself be a re-export alias there (package __init__ pattern).
        for cut in range(len(parts) - 1, 0, -1):
            modname = ".".join(parts[:cut])
            owner = self.modules_by_name.get(modname)
            if owner is None:
                continue
            rest = parts[cut:]
            local = ".".join(rest)
            if local in owner.functions:
                return owner.functions[local]
            if rest[0] in owner.aliases:
                target = ".".join(owner.aliases[rest[0]].split(".")
                                  + rest[1:])
                return self.resolve_function(owner, target, _seen=_seen)
            return None
        return None


def build_project(file_sources):
    """Project from ``{repo-relative posix path: source}``. Files that do
    not parse are skipped (their syntax errors are reported by the
    per-file pass)."""
    project = Project()
    for path in sorted(file_sources):
        try:
            project.add_source(path, file_sources[path])
        except SyntaxError:
            continue
    return project


def project_from_paths(paths, root):
    """Convenience: build a Project straight from disk paths (used by the
    fixture tests; run_check goes through the cache instead)."""
    from .core import iter_python_files
    sources = {}
    for abspath, relpath in iter_python_files(paths, root=root):
        with open(abspath, "r", encoding="utf-8") as f:
            sources[relpath] = f.read()
    return build_project(sources)
