"""Turn lddl_tpu trace JSONL files into a per-stage wall-time table.

Usage::

    python tools/trace_summary.py <metrics_dir_or_trace.jsonl> [...]

Reads every ``trace-*.jsonl`` under the given directories (or the files
given directly), groups complete ("ph": "X") events by span name, and
prints per-span and per-stage (name prefix before the first dot) rollups:
count, total wall time, mean and max. Instant events are tallied by name.

The input is the Chrome Trace Event format the observability layer emits
(one JSON object per line; a leading ``[`` / trailing ``]`` from a
hand-wrapped file is tolerated), so the same files open in Perfetto.
"""

import argparse
import json
import os
import sys


def iter_events(path):
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip().rstrip(",")
            if not line or line in ("[", "]"):
                continue
            if line.startswith("["):
                line = line[1:]
            if line.endswith("]"):
                line = line[:-1]
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if isinstance(ev, dict):
                yield ev


def collect(paths):
    """{span_name: {count, total_us, max_us}}, {instant_name: count}."""
    spans, instants = {}, {}
    for path in paths:
        for ev in iter_events(path):
            ph = ev.get("ph")
            name = ev.get("name")
            if not name:
                continue
            if ph == "X":
                st = spans.setdefault(name,
                                      {"count": 0, "total_us": 0.0,
                                       "max_us": 0.0})
                dur = float(ev.get("dur", 0.0))
                st["count"] += 1
                st["total_us"] += dur
                if dur > st["max_us"]:
                    st["max_us"] = dur
            elif ph == "i":
                instants[name] = instants.get(name, 0) + 1
    return spans, instants


def stage_of(name):
    return name.split(".", 1)[0]


def rollup_stages(spans):
    stages = {}
    for name, st in spans.items():
        agg = stages.setdefault(stage_of(name),
                                {"count": 0, "total_us": 0.0, "max_us": 0.0})
        agg["count"] += st["count"]
        agg["total_us"] += st["total_us"]
        if st["max_us"] > agg["max_us"]:
            agg["max_us"] = st["max_us"]
    return stages


def _table(rows, headers):
    widths = [max(len(str(r[i])) for r in rows + [headers])
              for i in range(len(headers))]
    lines = []
    for r in [headers, ["-" * w for w in widths]] + rows:
        lines.append("  ".join(
            str(c).ljust(w) if i == 0 else str(c).rjust(w)
            for i, (c, w) in enumerate(zip(r, widths))))
    return "\n".join(lines)


def format_summary(spans, instants):
    def fmt_rows(d):
        rows = []
        for name, st in sorted(d.items(), key=lambda kv: -kv[1]["total_us"]):
            mean_ms = st["total_us"] / st["count"] / 1e3 if st["count"] else 0
            rows.append([name, st["count"],
                         "{:.3f}".format(st["total_us"] / 1e6),
                         "{:.2f}".format(mean_ms),
                         "{:.2f}".format(st["max_us"] / 1e3)])
        return rows

    out = []
    if spans:
        out.append("per-stage wall time:")
        out.append(_table(fmt_rows(rollup_stages(spans)),
                          ["stage", "spans", "total_s", "mean_ms", "max_ms"]))
        out.append("")
        out.append("per-span wall time:")
        out.append(_table(fmt_rows(spans),
                          ["span", "count", "total_s", "mean_ms", "max_ms"]))
    else:
        out.append("no complete span events found")
    if instants:
        out.append("")
        out.append("instant events:")
        out.append(_table(
            [[n, c] for n, c in sorted(instants.items(),
                                       key=lambda kv: -kv[1])],
            ["event", "count"]))
    return "\n".join(out)


def resolve_paths(args_paths):
    paths = []
    for p in args_paths:
        if os.path.isdir(p):
            paths.extend(
                os.path.join(p, n) for n in sorted(os.listdir(p))
                if n.startswith("trace-") and n.endswith(".jsonl"))
        else:
            paths.append(p)
    return paths


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+",
                    help="metrics dir(s) and/or trace-*.jsonl file(s)")
    args = ap.parse_args(argv)
    paths = resolve_paths(args.paths)
    if not paths:
        print("no trace files found under {}".format(args.paths),
              file=sys.stderr)
        return 1
    spans, instants = collect(paths)
    print("{} trace file(s), {} span(s), {} instant event(s)".format(
        len(paths), sum(s["count"] for s in spans.values()),
        sum(instants.values())))
    print(format_summary(spans, instants))
    return 0


if __name__ == "__main__":
    sys.exit(main())
