"""Turn lddl_tpu trace JSONL files into a per-stage wall-time table, or
merge a whole fleet's traces into one aligned timeline.

Usage::

    python tools/trace_summary.py <metrics_dir_or_trace.jsonl> [...]
    python tools/trace_summary.py <dataset_dir> --merge merged.json

Summary mode reads every ``trace-*.jsonl`` under the given directories
(including per-host fleet spools under ``.telemetry/<holder>/``) or the
files given directly, groups complete ("ph": "X") events by span name,
and prints per-span and per-stage (name prefix before the first dot)
rollups: count, total wall time, mean and max. Instant events are
tallied by name. Multi-host/multi-pid inputs land on one table.

``--merge OUT.json`` additionally writes ONE Chrome trace spanning every
host spool under ``<dir>/.telemetry/``: per-(host, pid) Perfetto lanes
named after the holder, with each host's events re-anchored through its
published (wall, mono) clock samples so a wall-clock step on one host
cannot skew the merged timeline (see observability/fleet.merge_traces).

The input is the Chrome Trace Event format the observability layer emits
(one JSON object per line; a leading ``[`` / trailing ``]`` from a
hand-wrapped file is tolerated), so the same files open in Perfetto. A
torn trailing line — a host SIGKILLed mid-append — is reported as
end-of-stream with a warning, never an error.
"""

import argparse
import json
import os
import sys


def iter_events(path):
    """Stream events line-by-line (fleet trace files run to hundreds of
    MB — never slurp). One unparseable line of lookahead distinguishes a
    torn TRAILING line (a writer died mid-append: end-of-stream with a
    warning) from a torn interior one (skipped with a warning)."""
    torn_at = None  # line number of the last unparsed line, pending EOF
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip().rstrip(",")
            if not line or line in ("[", "]"):
                continue
            if torn_at is not None:
                print("warning: unparseable line {} in {}; skipped".format(
                    torn_at + 1, path), file=sys.stderr)
                torn_at = None
            if line.startswith("["):
                line = line[1:]
            if line.endswith("]"):
                line = line[:-1]
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                torn_at = i
                continue
            if isinstance(ev, dict):
                yield ev
    if torn_at is not None:
        print("warning: torn trailing line in {} (writer died "
              "mid-append?); treating as end-of-stream".format(path),
              file=sys.stderr)


def collect(paths):
    """{span_name: {count, total_us, max_us}}, {instant_name: count}."""
    spans, instants = {}, {}
    for path in paths:
        for ev in iter_events(path):
            ph = ev.get("ph")
            name = ev.get("name")
            if not name:
                continue
            if ph == "X":
                st = spans.setdefault(name,
                                      {"count": 0, "total_us": 0.0,
                                       "max_us": 0.0})
                dur = float(ev.get("dur", 0.0))
                st["count"] += 1
                st["total_us"] += dur
                if dur > st["max_us"]:
                    st["max_us"] = dur
            elif ph == "i":
                instants[name] = instants.get(name, 0) + 1
    return spans, instants


def stage_of(name):
    return name.split(".", 1)[0]


def rollup_stages(spans):
    stages = {}
    for name, st in spans.items():
        agg = stages.setdefault(stage_of(name),
                                {"count": 0, "total_us": 0.0, "max_us": 0.0})
        agg["count"] += st["count"]
        agg["total_us"] += st["total_us"]
        if st["max_us"] > agg["max_us"]:
            agg["max_us"] = st["max_us"]
    return stages


def _table(rows, headers):
    widths = [max(len(str(r[i])) for r in rows + [headers])
              for i in range(len(headers))]
    lines = []
    for r in [headers, ["-" * w for w in widths]] + rows:
        lines.append("  ".join(
            str(c).ljust(w) if i == 0 else str(c).rjust(w)
            for i, (c, w) in enumerate(zip(r, widths))))
    return "\n".join(lines)


def format_summary(spans, instants):
    def fmt_rows(d):
        rows = []
        for name, st in sorted(d.items(), key=lambda kv: -kv[1]["total_us"]):
            mean_ms = st["total_us"] / st["count"] / 1e3 if st["count"] else 0
            rows.append([name, st["count"],
                         "{:.3f}".format(st["total_us"] / 1e6),
                         "{:.2f}".format(mean_ms),
                         "{:.2f}".format(st["max_us"] / 1e3)])
        return rows

    out = []
    if spans:
        out.append("per-stage wall time:")
        out.append(_table(fmt_rows(rollup_stages(spans)),
                          ["stage", "spans", "total_s", "mean_ms", "max_ms"]))
        out.append("")
        out.append("per-span wall time:")
        out.append(_table(fmt_rows(spans),
                          ["span", "count", "total_s", "mean_ms", "max_ms"]))
    else:
        out.append("no complete span events found")
    if instants:
        out.append("")
        out.append("instant events:")
        out.append(_table(
            [[n, c] for n, c in sorted(instants.items(),
                                       key=lambda kv: -kv[1])],
            ["event", "count"]))
    return "\n".join(out)


def _trace_files_in(d):
    return [os.path.join(d, n) for n in sorted(os.listdir(d))
            if n.startswith("trace-") and n.endswith(".jsonl")]


def resolve_paths(args_paths):
    """Trace files named directly, found in the given dirs, and found in
    any per-host fleet spool (``<dir>/.telemetry/<holder>/``) below
    them — so `trace_summary <dataset_dir>` covers the whole fleet."""
    paths = []
    for p in args_paths:
        if os.path.isdir(p):
            paths.extend(_trace_files_in(p))
            tele = os.path.join(p, ".telemetry")
            if os.path.isdir(tele):
                for holder in sorted(os.listdir(tele)):
                    spool = os.path.join(tele, holder)
                    if os.path.isdir(spool):
                        paths.extend(_trace_files_in(spool))
        else:
            paths.append(p)
    return paths


def write_merged(dirs, out_path):
    """Merge every fleet spool under the given dataset dirs into one
    clock-aligned Chrome trace at ``out_path``."""
    from lddl_tpu.observability import fleet

    events, lanes = [], []
    for d in dirs:
        ev, ln = fleet.merge_traces(d)
        base = len(lanes)
        for rec in ev:
            if "pid" in rec:
                rec = dict(rec, pid=rec["pid"] + base)
            events.append(rec)
        lanes.extend((lane + base, holder, pid) for lane, holder, pid in ln)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(events, f)
    return events, lanes


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+",
                    help="metrics/dataset dir(s) and/or trace-*.jsonl "
                         "file(s)")
    ap.add_argument("--merge", default=None, metavar="OUT.json",
                    help="write one clock-aligned Chrome trace merging "
                         "every host spool under the given dir(s) "
                         "(requires dir arguments with .telemetry/)")
    args = ap.parse_args(argv)
    if args.merge:
        dirs = [p for p in args.paths if os.path.isdir(p)]
        if not dirs:
            print("--merge needs dataset dir argument(s) containing "
                  ".telemetry/", file=sys.stderr)
            return 1
        events, lanes = write_merged(dirs, args.merge)
        print("merged trace: {} ({} event(s) across {} lane(s): {})".format(
            args.merge, len(events), len(lanes),
            ", ".join("{} pid{}".format(h, p) for _, h, p in lanes)))
    paths = resolve_paths(args.paths)
    if not paths:
        print("no trace files found under {}".format(args.paths),
              file=sys.stderr)
        return 1
    spans, instants = collect(paths)
    print("{} trace file(s), {} span(s), {} instant event(s)".format(
        len(paths), sum(s["count"] for s in spans.values()),
        sum(instants.values())))
    print(format_summary(spans, instants))
    return 0


if __name__ == "__main__":
    sys.exit(main())
