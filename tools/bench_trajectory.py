"""Bench-trajectory tracker: regression/improvement table over the
committed benchmark artifact series.

Usage::

    python -m tools.bench_trajectory [--repo-root DIR] [--json]

Reads the checked-in ``BENCH_r*.json`` preprocess-headline series and
``LOADER_BENCH.json``, and prints a calibration-normalized trajectory
table. The ROADMAP rule is **compare calibrations, not rounds**: the
bench VM drifts between rounds, so a raw MB/s delta conflates code
changes with host changes. Rounds that recorded
``parsed.config.host_calibration_s`` (the wall time of a fixed reference
workload on that round's host — larger = slower host) are normalized to
the newest calibrated round's host speed::

    normalized = value * (host_calibration_s / reference_calibration_s)

Rounds without a calibration (r01–r03 predate it) print raw with an
``uncal`` marker and are excluded from the verdict. The final verdict
line compares the newest calibrated round against the previous one and
is **informational only** — ``tools/ci_check.sh`` runs this non-gating,
the exit status is always 0 when the artifacts parse.
"""

import argparse
import glob
import json
import os
import sys

try:
    from tools.trace_summary import _table  # python -m tools.*
except ImportError:  # direct script invocation: tools/ is sys.path[0]
    from trace_summary import _table


def load_bench_series(repo_root):
    """[(round_tag, value_mb_s, calibration_s_or_None)] sorted by round."""
    rows = []
    for path in sorted(glob.glob(os.path.join(repo_root, "BENCH_r*.json"))):
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print("warning: unreadable bench artifact {} ({}); skipped"
                  .format(path, e), file=sys.stderr)
            continue
        parsed = doc.get("parsed") or {}
        value = parsed.get("value")
        if value is None:
            continue
        cal = (parsed.get("config") or {}).get("host_calibration_s")
        tag = os.path.basename(path)[len("BENCH_"):-len(".json")]
        rows.append((tag, float(value),
                     float(cal) if cal is not None else None))
    return rows


def normalize(rows):
    """Attach a calibration-normalized value per row (None when the row
    or the series has no calibration). Reference = the NEWEST calibrated
    round, so the latest number reads unchanged and history is restated
    in today's host-speed units."""
    ref = None
    for _, _, cal in reversed(rows):
        if cal is not None:
            ref = cal
            break
    out = []
    for tag, value, cal in rows:
        norm = value * (cal / ref) if (cal is not None and ref) else None
        out.append({"round": tag, "mb_per_s": value, "calibration_s": cal,
                    "normalized_mb_per_s": norm})
    return out


def verdict(series):
    cal_rounds = [r for r in series if r["normalized_mb_per_s"] is not None]
    if len(cal_rounds) < 2:
        return {"verdict": "insufficient calibrated rounds", "delta_pct": None}
    prev, last = cal_rounds[-2], cal_rounds[-1]
    delta = (last["normalized_mb_per_s"] / prev["normalized_mb_per_s"]
             - 1.0) * 100.0
    word = ("improvement" if delta > 2.0 else
            "regression" if delta < -2.0 else "flat")
    return {
        "verdict": word,
        "delta_pct": delta,
        "from_round": prev["round"],
        "to_round": last["round"],
    }


def load_sink_overlap(repo_root):
    """The async-sink overlap block from PROFILE_PREPROCESS.json (writer-
    thread seconds vs producer stall — how much durable-sink work left
    the critical path), or None when the artifact predates it."""
    path = os.path.join(repo_root, "PROFILE_PREPROCESS.json")
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    overlap = doc.get("sink_overlap")
    if not isinstance(overlap, dict):
        return None
    out = dict(overlap)
    out["producer_mb_per_s"] = doc.get("mb_per_s_single_worker")
    prev = doc.get("previous") or {}
    out["previous_mb_per_s"] = prev.get("mb_per_s_single_worker")
    return out


def load_thread_scaling(repo_root):
    """The per-thread-count tokenize MB/s block (and sentence-memo win)
    from PROFILE_PREPROCESS.json — informational: a 1-core host records
    the rows without being able to show speedup. None when the artifact
    predates the v8 threaded kernel."""
    path = os.path.join(repo_root, "PROFILE_PREPROCESS.json")
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    scaling = doc.get("native_thread_scaling")
    if not isinstance(scaling, dict):
        return None
    out = dict(scaling)
    out["host_can_show_scaling"] = doc.get("host_can_show_scaling")
    memo = doc.get("sentence_memo")
    if isinstance(memo, dict):
        out["sentence_memo_speedup"] = memo.get("memo_speedup")
    return out


def load_static_analysis(repo_root):
    """Finding count + per-rule tally from the lddl_check.sarif artifact
    the ``tools/ci_check.sh --full`` gate writes, so the static-analysis
    verdict shows up on the same status surface as perf and alerts. New
    findings gate CI ("error" level); baselined ones ride along as
    "note"/baselineState=unchanged. None when no artifact exists."""
    path = os.path.join(repo_root, "lddl_check.sarif")
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        run = doc["runs"][0]
    except (OSError, ValueError, KeyError, IndexError):
        return None
    new, baselined = 0, 0
    by_rule = {}
    for res in run.get("results", ()):
        if res.get("baselineState") == "unchanged":
            baselined += 1
        else:
            new += 1
        rid = res.get("ruleId", "?")
        by_rule[rid] = by_rule.get(rid, 0) + 1
    return {
        "new": new,
        "baselined": baselined,
        "by_rule": by_rule,
        "rules_enabled": len(run.get("tool", {}).get("driver", {})
                             .get("rules", ())),
    }


def load_coordination(repo_root):
    """The elastic coordination-cost and autoscale-episode blocks from
    SCALE_RUN.json (lease filesystem ops per unit, legacy vs batched;
    gather overlap; steal latency; the recorded scale_up/scale_down
    episode), or None when the artifact predates phase 7."""
    path = os.path.join(repo_root, "SCALE_RUN.json")
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    phases = doc.get("phases") or {}
    coord = phases.get("coordination_cost")
    if not isinstance(coord, dict):
        return None
    out = {
        "ops_per_unit_legacy": (coord.get("legacy") or {}).get(
            "ops_per_unit"),
        "ops_per_unit_batched": (coord.get("batched_adaptive") or {}).get(
            "ops_per_unit"),
        "ops_per_unit_ratio": coord.get("ops_per_unit_ratio"),
        "gather_overlap_s": (coord.get("batched_adaptive") or {}).get(
            "gather_overlap_s"),
        "steal_latency_s_median": (coord.get("steal_leg") or {}).get(
            "steal_latency_s_median"),
        "host_can_show_scaling": coord.get("host_can_show_scaling"),
    }
    episode = phases.get("autoscale_episode")
    if isinstance(episode, dict):
        out["autoscale"] = {
            "decisions_total": episode.get("decisions_total"),
            "helper_joined_generation": episode.get(
                "helper_joined_generation"),
            "backlog_slo_docs": episode.get("backlog_slo_docs"),
        }
    return out


def load_loader_bench(repo_root):
    path = os.path.join(repo_root, "LOADER_BENCH.json")
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    out = {"unit": doc.get("unit")}
    speedup = doc.get("schema_v2_speedup") or {}
    out["schema_v2_over_v1"] = {
        k: v.get("v2_over_v1") for k, v in speedup.items()
        if isinstance(v, dict)
    }
    packed = doc.get("packed_offline_speedup") or {}
    out["packed_offline_over_loadtime"] = {
        k: {"x": v.get("offline_over_loadtime"),
            "pad_offline": v.get("offline_pad_ratio"),
            "pad_loadtime": v.get("loadtime_pad_ratio")}
        for k, v in packed.items() if isinstance(v, dict)
    }
    configs = doc.get("configs") or {}
    out["sustained_samples_per_s"] = {
        k: v.get("sustained_samples_per_s") for k, v in sorted(
            configs.items()) if isinstance(v, dict)
    }
    cache = doc.get("cache_prefetch_speedup") or {}
    if isinstance(cache, dict) and cache:
        out["cache_prefetch"] = {
            "backend_latency_ms": cache.get("backend_latency_ms"),
            "shards": cache.get("shards"),
            "prefetch_over_sync": cache.get("prefetch_over_sync"),
            "prefetch_over_local": cache.get("prefetch_over_local"),
            "warm_epoch_over_local_epoch": cache.get(
                "warm_epoch_over_local_epoch"),
        }
    return out


def load_live_rates(root, window_s):
    """Windowed per-metric rates from the time-series telemetry segments
    under ``<root>/.telemetry/`` (summed across hosts) — the live
    counterpart to the committed artifact series, so a trajectory check
    can be run against a fleet mid-flight, not only after artifacts
    land. None when the root has no telemetry."""
    try:
        from lddl_tpu.observability import fleet
        from lddl_tpu.observability import series as ts
    except ImportError:
        return None
    rates = {}
    for h in fleet.list_holders(root):
        points, _ = ts.read_series(root, h)
        roll = ts.window_rollup(points, window_s)
        for key, r in roll["rates"].items():
            rates[key] = rates.get(key, 0.0) + r
    if not rates:
        return None
    return {"window_s": window_s, "rates": rates}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--repo-root",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    help="directory holding the BENCH_r*.json artifacts")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable trajectory")
    ap.add_argument("--series-dir", default=None, metavar="DIR",
                    help="also read live time-series telemetry under "
                         "DIR/.telemetry and report windowed rates")
    ap.add_argument("--window", type=float, default=300.0,
                    help="--series-dir trailing window (seconds)")
    args = ap.parse_args(argv)
    series = normalize(load_bench_series(args.repo_root))
    result = {
        "preprocess_mb_per_s": series,
        "preprocess_verdict": verdict(series),
        "loader": load_loader_bench(args.repo_root),
        "sink_overlap": load_sink_overlap(args.repo_root),
        "coordination": load_coordination(args.repo_root),
        "thread_scaling": load_thread_scaling(args.repo_root),
        "static_analysis": load_static_analysis(args.repo_root),
    }
    if args.series_dir:
        result["live_rates"] = load_live_rates(args.series_dir, args.window)
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
        return 0
    if not series:
        print("no BENCH_r*.json artifacts under {}".format(args.repo_root))
        return 0
    rows = []
    prev_norm = None
    for r in series:
        norm = r["normalized_mb_per_s"]
        delta = ""
        if norm is not None and prev_norm is not None:
            delta = "{:+.1f}%".format((norm / prev_norm - 1.0) * 100.0)
        rows.append([
            r["round"],
            "{:.2f}".format(r["mb_per_s"]),
            "{:.3f}".format(r["calibration_s"])
            if r["calibration_s"] is not None else "uncal",
            "{:.2f}".format(norm) if norm is not None else "-",
            delta,
        ])
        if norm is not None:
            prev_norm = norm
    print("preprocess headline trajectory (normalized to the newest "
          "calibrated host):")
    print(_table(rows, ["round", "MB/s raw", "cal_s", "MB/s norm",
                        "delta"]))
    v = result["preprocess_verdict"]
    if v["delta_pct"] is not None:
        print("verdict: {} ({:+.1f}% {} -> {}, calibration-normalized)"
              .format(v["verdict"], v["delta_pct"], v["from_round"],
                      v["to_round"]))
    else:
        print("verdict: {}".format(v["verdict"]))
    loader = result["loader"]
    if loader and loader["schema_v2_over_v1"]:
        print("loader schema-v2 speedups: " + ", ".join(
            "{}={}x".format(k, v) for k, v in sorted(
                loader["schema_v2_over_v1"].items())))
    if loader and loader.get("cache_prefetch"):
        c = loader["cache_prefetch"]
        print("loader shard prefetch+cache (mock store, {}ms/op, {} "
              "shards): {}x over sync, {}x of local-FS, warm epoch "
              "{}x local".format(
                  c.get("backend_latency_ms"), c.get("shards"),
                  c.get("prefetch_over_sync"),
                  c.get("prefetch_over_local"),
                  c.get("warm_epoch_over_local_epoch")))
    if loader and loader.get("packed_offline_over_loadtime"):
        print("offline-packed over load-time packer: " + ", ".join(
            "{}={}x (pad {} vs {})".format(k, v["x"], v["pad_offline"],
                                           v["pad_loadtime"])
            for k, v in sorted(
                loader["packed_offline_over_loadtime"].items())))
    overlap = result["sink_overlap"]
    if overlap:
        line = ("async sink overlap (PROFILE_PREPROCESS): depth={depth}, "
                "{tasks} deferred publishes over {units} units, writer "
                "{write}s off the critical path, producer stalled "
                "{stall}s").format(
                    depth=overlap.get("async_depth"),
                    tasks=overlap.get("deferred_publishes"),
                    units=overlap.get("units"),
                    write=overlap.get("writer_write_s"),
                    stall=overlap.get("producer_stall_s"))
        if overlap.get("producer_mb_per_s") is not None \
                and overlap.get("previous_mb_per_s") is not None:
            line += "; single-worker {} -> {} MB/s".format(
                overlap["previous_mb_per_s"], overlap["producer_mb_per_s"])
        print(line)
    threads = result["thread_scaling"]
    if threads and threads.get("tokenize_mb_per_s_by_threads"):
        rows_t = threads["tokenize_mb_per_s_by_threads"]
        line = ("native thread scaling (PROFILE_PREPROCESS, "
                "informational): tokenize " + ", ".join(
                    "{}t={} MB/s".format(k, rows_t[k])
                    for k in sorted(rows_t, key=int)))
        if threads.get("speedup_2_threads") is not None:
            line += " ({}x at 2 threads)".format(
                threads["speedup_2_threads"])
        if threads.get("sentence_memo_speedup") is not None:
            line += "; sentence-memo win {}x on repeated buckets".format(
                threads["sentence_memo_speedup"])
        if not threads.get("host_can_show_scaling"):
            line += " [host too small to show scaling]"
        print(line)
    coord = result["coordination"]
    if coord:
        print("elastic coordination (SCALE_RUN phase 7): lease FS "
              "ops/unit {} legacy -> {} batched ({}x), gather overlap "
              "{}s, steal latency median {}s{}".format(
                  coord.get("ops_per_unit_legacy"),
                  coord.get("ops_per_unit_batched"),
                  coord.get("ops_per_unit_ratio"),
                  coord.get("gather_overlap_s"),
                  coord.get("steal_latency_s_median"),
                  "" if coord.get("host_can_show_scaling")
                  else " [host too small to show scaling]"))
        scale = coord.get("autoscale")
        if scale:
            print("autoscale episode (phase 8): decisions {} at SLO {} "
                  "docs, helper joined in-flight generation: {}".format(
                      scale.get("decisions_total"),
                      scale.get("backlog_slo_docs"),
                      scale.get("helper_joined_generation")))
    sa = result["static_analysis"]
    if sa:
        tally = ", ".join("{}={}".format(k, v)
                          for k, v in sorted(sa["by_rule"].items()))
        print("static analysis (lddl_check.sarif): {} new, {} baselined "
              "finding(s) across {} rules{}".format(
                  sa["new"], sa["baselined"], sa["rules_enabled"],
                  "; by rule: " + tally if tally else ""))
    live = result.get("live_rates")
    if live:
        print("live rates (last {:.0f}s from {}):".format(
            live["window_s"], args.series_dir))
        print(_table(
            [[k, "{:.3g}/s".format(v)]
             for k, v in sorted(live["rates"].items())],
            ["metric", "rate"]))
    elif args.series_dir:
        print("no series telemetry found under {}/.telemetry".format(
            args.series_dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
