#!/usr/bin/env bash
# Fast static gate: the determinism/SPMD-safety analyzer plus a
# whole-tree syntax pass (pyflakes when available, compileall otherwise).
#
# Two modes:
#   tools/ci_check.sh            pre-commit default: report findings only
#                                for files changed vs git HEAD (the flow
#                                analysis still spans the whole tree, and
#                                the content-hash cache makes the warm run
#                                sub-second)
#   tools/ci_check.sh --full     the tier-1 CI gate (wired via
#                                tests/test_analysis.py::test_ci_check_script):
#                                full-tree report + lddl_check.sarif
#                                artifact for code-review tooling
#
# Extra arguments after the mode flag pass through to tools.lddl_check.
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="changed"
if [ "${1:-}" = "--full" ]; then
    MODE="full"
    shift
fi

if [ "$MODE" = "full" ]; then
    python -m tools.lddl_check --sarif lddl_check.sarif "$@"
    echo "ci_check: SARIF artifact written to lddl_check.sarif"
else
    python -m tools.lddl_check --changed-only "$@"
fi

if python -c "import pyflakes" >/dev/null 2>&1; then
    python -m pyflakes lddl_tpu tools benchmarks
else
    python -m compileall -q lddl_tpu tools benchmarks
fi

# Non-gating bench trajectory: a calibration-normalized regression/
# improvement table over the committed BENCH_r*.json / LOADER_BENCH.json
# series ("compare calibrations, not rounds"). Informational only: a
# parse failure or a regression verdict must not fail the static gate.
if python -m tools.bench_trajectory; then
    :
else
    echo "ci_check: bench_trajectory FAILED (non-gating, ignored)" >&2
fi

# Non-gating loader health sample: a 1 MB loader_bench smoke (the
# v1-vs-v2 unbinned pair PLUS the offline-packed vs load-time-packed
# pair) that publishes LOADER_BENCH_SMOKE.json as a CI artifact. Opt-in
# via LDDL_TPU_CI_SMOKE_BENCH=1 (it costs ~a minute of preprocessing,
# which the static gate itself must not) and NEVER fails the check — the
# artifact is for humans watching throughput drift, not a hard gate.
if [ "${LDDL_TPU_CI_SMOKE_BENCH:-0}" = "1" ]; then
    if JAX_PLATFORMS=cpu python benchmarks/loader_bench.py --smoke; then
        echo "ci_check: loader_bench smoke artifact written (non-gating)"
    else
        echo "ci_check: loader_bench smoke FAILED (non-gating, ignored)" >&2
    fi
    # Async-sink serial-vs-async smoke pair: the timing is informational,
    # but the script itself asserts serial/async byte identity and exits
    # nonzero on divergence — that half IS a correctness alarm.
    if JAX_PLATFORMS=cpu python benchmarks/sink_smoke.py; then
        echo "ci_check: sink serial-vs-async smoke pair OK (timing non-gating)"
    else
        echo "ci_check: sink smoke FAILED — serial/async divergence or crash" >&2
        exit 1
    fi
    # Elastic coordination smoke: two worksteal processes, legacy vs
    # batched coordination, on a tiny corpus. The byte-identity half is
    # gating (the lease protocol must never reach shard bytes); the
    # lease-ops-per-unit ratio it prints is informational — the
    # committed SCALE_RUN.json phase 7 is the measurement of record.
    if JAX_PLATFORMS=cpu python benchmarks/elastic_smoke.py; then
        echo "ci_check: elastic coordination smoke OK (ratio non-gating)"
    else
        echo "ci_check: elastic smoke FAILED — legacy/batched divergence or crash" >&2
        exit 1
    fi
    # Storage-backend smoke: the same preprocess -> balance -> load
    # round trip on the default LocalBackend vs the MockObjectStore
    # (--storage-backend mock). Byte identity is GATING — the backend
    # is publish/coordination plumbing and must never reach shard
    # bytes; the wall times it prints are informational.
    if JAX_PLATFORMS=cpu python benchmarks/backend_smoke.py; then
        echo "ci_check: storage-backend local-vs-mock smoke OK (walls non-gating)"
    else
        echo "ci_check: backend smoke FAILED — local/mock divergence or crash" >&2
        exit 1
    fi
    # Native thread-pool smoke: the same preprocess run at 1 kernel
    # thread vs N. Byte identity (shards + manifests) is GATING — the
    # per-sample-keyed RNG contracts make partitioning invisible in the
    # output, so any divergence is a kernel bug; the per-thread-count
    # tokenize MB/s rows it prints are informational.
    if JAX_PLATFORMS=cpu python benchmarks/thread_smoke.py; then
        echo "ci_check: native 1-vs-N thread identity smoke OK (MB/s non-gating)"
    else
        echo "ci_check: thread smoke FAILED — 1-vs-N thread divergence or crash" >&2
        exit 1
    fi
    # Diagnosis-surface smoke: a tiny fleet-armed preprocess -> balance
    # -> load run, then pipeline_status driven as an operator would.
    # GATING: `--json --window` must parse with windowed series rates
    # and a loader bound-verdict, a tripped alert rule must force exit
    # code 2, and the relaxed rules file must journal the resolve.
    if JAX_PLATFORMS=cpu python benchmarks/status_smoke.py; then
        echo "ci_check: pipeline_status diagnosis smoke OK"
    else
        echo "ci_check: status smoke FAILED — attribution/window/alert contract broken" >&2
        exit 1
    fi
    # Loader shard-I/O pipeline smoke: sync vs prefetch+cache (cold and
    # warm) over a latency-injected mock store. Byte identity is GATING
    # — prefetch depth and cache budget are scheduling knobs and must
    # never change a delivered tensor byte; the speedups it prints are
    # informational (LOADER_BENCH.json cache_prefetch_speedup is the
    # measurement of record).
    if JAX_PLATFORMS=cpu python benchmarks/cache_smoke.py; then
        echo "ci_check: loader prefetch/cache identity smoke OK (speedup non-gating)"
    else
        echo "ci_check: cache smoke FAILED — prefetch/cache changed delivered bytes or crash" >&2
        exit 1
    fi
fi

# Opt-in native-engine smoke: builds the C++ engine from source and runs
# the fused-vs-staged-vs-hf shard byte-identity test (the contract the
# fused hot path lives under). GATING when requested: a build that
# silently fell back to the hf engine would pass the identity test
# vacuously, so the build step itself must succeed too. Opt-in via
# LDDL_TPU_CI_SMOKE_NATIVE=1 (costs ~a minute; the static gate itself
# must stay sub-second).
if [ "${LDDL_TPU_CI_SMOKE_NATIVE:-0}" = "1" ]; then
    JAX_PLATFORMS=cpu python -m lddl_tpu.native.build
    JAX_PLATFORMS=cpu python -m pytest tests/test_fused.py -q \
        -k "identity_smoke or mask_matches" -p no:cacheprovider
    echo "ci_check: native fused identity smoke passed"
fi

# Opt-in sanitizer smoke: rebuilds the kernel under TSan+UBSan (its own
# mode-suffixed .so, so the normal build cache is untouched) and runs
# the 1-vs-N entry-point identity suite against it. GATING when
# requested: any sanitizer report, a failed instrumented build, or the
# sanitized engine silently failing to load all exit nonzero. Opt-in
# via LDDL_TPU_CI_SMOKE_SANITIZE=1 (instrumented build + TSan-slowed
# suite costs minutes; the static gate itself must stay sub-second).
if [ "${LDDL_TPU_CI_SMOKE_SANITIZE:-0}" = "1" ]; then
    if JAX_PLATFORMS=cpu python benchmarks/sanitize_smoke.py; then
        echo "ci_check: sanitize smoke passed (TSan+UBSan, zero reports)"
    else
        echo "ci_check: sanitize smoke FAILED — sanitizer report or instrumented build/load failure" >&2
        exit 1
    fi
fi
echo "ci_check: OK"
