#!/usr/bin/env bash
# Fast static gate: the determinism/SPMD-safety analyzer plus a
# whole-tree syntax pass (pyflakes when available, compileall otherwise).
# Wired into tier-1 via tests/test_analysis.py::test_ci_check_script.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m tools.lddl_check "$@"

if python -c "import pyflakes" >/dev/null 2>&1; then
    python -m pyflakes lddl_tpu tools benchmarks
else
    python -m compileall -q lddl_tpu tools benchmarks
fi
echo "ci_check: OK"
