"""Fleet/pipeline health monitor: one-shot report, --watch, or --json.

Usage::

    python -m tools.pipeline_status <dataset_or_output_dir>
    python -m tools.pipeline_status <dir> --watch [--interval 5]
    python -m tools.pipeline_status <dir> --json        # CI / benchmarks

Reads the per-host telemetry spools under ``<dir>/.telemetry/`` (written
by hosts running with ``LDDL_TPU_FLEET_DIR=<dir>`` or
``--fleet-telemetry``; see lddl_tpu/observability/fleet.py) and renders
cluster rollups with explicit health verdicts:

- a host is **STALLED** when its last heartbeat is older than the stall
  TTL (default: the lease TTL the host advertised) and it left no
  clean-shutdown marker — the same condition under which the elastic
  scheduler lets survivors steal the host's units;
- the service is **WEDGED** when live hosts and pending work exist but
  the journal/ledger has made no progress inside the wedge window.

``--window SECONDS`` additionally reads the time-series segments each
host's heartbeat spools (series-pid*.jsonl) and renders windowed rates
with sparklines and gauge trends — "what is happening NOW", not lifetime
averages. ``--alerts rules.json`` evaluates a declarative alert-rules
file (threshold / rate-over-window / absence; see
lddl_tpu/observability/alerts.py for the schema) against the same
rollup; firing/resolving transitions are journaled under
``.telemetry/`` so one-shot invocations see them too.

Exit status: 0 when healthy, 2 when any verdict fired OR any alert rule
is firing (``--json`` too, so CI can gate on it). ``--merge-trace
out.json`` additionally writes one clock-aligned Chrome trace spanning
every host (open in Perfetto); ``tools/trace_summary.py --merge`` does
the same plus summary tables.

All wall-clock reads happen inside ``fleet.aggregate`` (observability is
the one layer allowlisted for them); this tool only formats the report.
"""

import argparse
import json
import os
import sys
import time

try:
    from tools.trace_summary import _table  # python -m tools.*
    from tools.bench_trajectory import load_static_analysis
except ImportError:  # direct script invocation: tools/ is sys.path[0]
    from trace_summary import _table
    from bench_trajectory import load_static_analysis


def _fmt_age(age):
    if age is None:
        return "-"
    if age < 120:
        return "{:.1f}s".format(age)
    if age < 7200:
        return "{:.1f}m".format(age / 60.0)
    return "{:.1f}h".format(age / 3600.0)


def _fmt_rate(v, unit):
    if v is None:
        return "-"
    return "{:.2f}{}".format(v, unit)


def _host_status(st):
    if st["stalled"]:
        return "STALLED"
    if st["closed"]:
        return "closed"
    return "live"


_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _spark(values, width=24):
    """A sparkline over a value sequence, resampled to ``width`` bins by
    summing (the inputs are deltas, so summing preserves totals)."""
    if not values:
        return ""
    if len(values) > width:
        bins = [0.0] * width
        for i, v in enumerate(values):
            bins[i * width // len(values)] += v
        values = bins
    hi = max(values)
    if hi <= 0:
        return _SPARK_CHARS[0] * len(values)
    return "".join(
        _SPARK_CHARS[min(len(_SPARK_CHARS) - 1,
                         int(v / hi * (len(_SPARK_CHARS) - 1) + 0.5))]
        for v in values)


def _trend_arrow(trend):
    if trend is None:
        return ""
    if trend > 0:
        return "↑"
    if trend < 0:
        return "↓"
    return "→"


def _window_sections(report):
    """(rate_rows, gauge_rows) for the --window tables, merged across
    hosts (each row keeps its host column so a skewed host stands out)."""
    rate_rows, gauge_rows = [], []
    for name in sorted(report["hosts"]):
        win = report["hosts"][name].get("window")
        if not win:
            continue
        for key in sorted(win["rates"]):
            deltas = [dv for _, dv in win["deltas"].get(key, ())]
            rate_rows.append([name, key,
                              "{:.3g}/s".format(win["rates"][key]),
                              _spark(deltas)])
        for key in sorted(win["gauges"]):
            g = win["gauges"][key]
            gauge_rows.append([name, key, "{:.4g}".format(g["last"]),
                               _trend_arrow(g.get("trend"))])
    return rate_rows, gauge_rows


def format_report(report):
    out = []
    health = report["health"]
    out.append("pipeline status: {}".format(report["root"]))
    out.append("overall: {}".format("OK" if health["ok"] else "UNHEALTHY"))
    gen = report.get("journal_generation")
    bits = []
    if gen is not None:
        bits.append("ingest journal at generation {}".format(gen))
    if report.get("pending_work"):
        bits.append("pending work: {}".format(report["pending_work"]))
    fill = report["totals"]["counters"].get("pack_fill_ratio")
    if fill is not None:
        bits.append("offline pack fill {:.4f} (tokens placed / budget "
                    "slots)".format(fill))
    if bits:
        out.append("; ".join(bits))
    hosts = report["hosts"]
    if not hosts:
        out.append("no telemetry spools found under {}/.telemetry/ — run "
                   "hosts with --fleet-telemetry or LDDL_TPU_FLEET_DIR"
                   .format(report["root"]))
    else:
        rows = []
        for name in sorted(hosts):
            st = hosts[name]
            c = st["counters"]
            rows.append([
                name,
                _host_status(st),
                _fmt_age(st["heartbeat_age_s"]),
                c["units_completed"],
                c["steals"],
                c["fence_rejects"],
                c["retries"],
                _fmt_rate(st["rates"].get("units_per_s"), "/s"),
                _fmt_rate(st["rates"].get("mb_per_s"), ""),
                st["torn_lines"] or "",
            ])
        totals = report["totals"]
        rows.append([
            "TOTAL", "", "",
            totals["counters"]["units_completed"],
            totals["counters"]["steals"],
            totals["counters"]["fence_rejects"],
            totals["counters"]["retries"],
            _fmt_rate(totals["rates"].get("units_per_s"), "/s"),
            _fmt_rate(totals["rates"].get("mb_per_s"), ""),
            "",
        ])
        out.append("")
        out.append(_table(rows, ["host", "state", "beat", "units",
                                 "steals", "fenced", "retries", "units/s",
                                 "MB/s", "torn"]))
        gauge_rows = []
        for name in sorted(hosts):
            for key, val in sorted(hosts[name]["gauges"].items()):
                gauge_rows.append([name, key,
                                   "{:.4g}".format(val)
                                   if isinstance(val, float) else val])
        if gauge_rows:
            out.append("")
            out.append(_table(gauge_rows, ["host", "gauge", "value"]))
        events = {}
        for st in hosts.values():
            for kind, n in st["event_counts"].items():
                events[kind] = events.get(kind, 0) + n
        if events:
            out.append("")
            out.append(_table(
                [[k, n] for k, n in sorted(events.items(),
                                           key=lambda kv: -kv[1])],
                ["lifecycle event", "count"]))
    attr = report.get("attribution")
    if attr:
        from lddl_tpu.observability import attribution as attr_mod
        out.append("")
        out.append(attr_mod.format_report(attr))
    backend = report.get("backend") or {}
    if backend.get("ops") or backend.get("latency"):
        lat = backend.get("latency") or {}
        rows = []
        for label, n in sorted(backend.get("ops", {}).items()):
            stats = lat.get(_strip_outcome(label), {})
            rows.append([label, n,
                         "{:.2f}ms".format(stats["mean"] * 1e3)
                         if stats.get("mean") is not None else "-",
                         "{:.2f}ms".format(stats["max"] * 1e3)
                         if stats.get("max") is not None else "-"])
        out.append("")
        out.append(_table(rows, ["backend op", "count", "mean", "max"]))
    rate_rows, gauge_rows = _window_sections(report)
    if rate_rows or gauge_rows:
        out.append("")
        out.append("window: last {:.0f}s".format(
            report.get("window", {}).get("window_s", 0.0)))
        if rate_rows:
            out.append(_table(rate_rows, ["host", "metric", "rate",
                                          "trend"]))
        if gauge_rows:
            out.append(_table(gauge_rows, ["host", "gauge", "last", ""]))
    sa = report.get("static_analysis")
    if sa:
        tally = ", ".join("{}={}".format(k, v)
                          for k, v in sorted(sa["by_rule"].items()))
        out.append("")
        out.append("static analysis: {} new, {} baselined finding(s)"
                   "{}".format(sa["new"], sa["baselined"],
                               "; by rule: " + tally if tally else ""))
    alerts = report.get("alerts")
    if alerts:
        out.append("")
        for a in alerts["alerts"]:
            state = "FIRING" if a["firing"] else (
                "error" if a.get("error") else "ok")
            detail = a.get("error") or "value={}".format(
                "-" if a["value"] is None else "{:.4g}".format(a["value"])
                if isinstance(a["value"], float) else a["value"])
            out.append("alert {:<24s} [{}] {}".format(
                a["name"], state, detail))
    out.append("")
    if health["verdicts"]:
        for v in health["verdicts"]:
            out.append("!! {}".format(v))
    else:
        out.append("no health verdicts fired")
    if alerts and alerts["firing"]:
        out.append("!! alert(s) firing: {}".format(
            ", ".join(alerts["firing"])))
    return "\n".join(out)


def _strip_outcome(label):
    """backend_ops_total labels carry an outcome the latency histogram
    does not — drop it so the two join on {backend,op}."""
    return ",".join(part for part in label.split(",")
                    if not part.startswith("outcome="))


def run_once(args):
    from lddl_tpu.observability import fleet
    from lddl_tpu.resilience import backend as storage

    report = fleet.aggregate(args.dir, stall_ttl=args.stall_ttl,
                             wedge_window=args.wedge_window,
                             window=args.window)
    # The backend this process would coordinate through (env-selected;
    # chaos/CI runs export LDDL_TPU_STORAGE_BACKEND into the whole
    # fleet, so the operator's status probe names the same store).
    report["storage_backend"] = storage.active_name()
    # Static-analysis verdict from the ci_check --full SARIF artifact, so
    # the operator sees the gate on the same surface as perf and alerts.
    report["static_analysis"] = load_static_analysis(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if args.alerts:
        from lddl_tpu.observability import alerts as alerts_mod
        report["alerts"] = alerts_mod.evaluate_file(
            args.dir, args.alerts, report=report)
    if args.merge_trace:
        events, lanes = fleet.merge_traces(args.dir)
        with open(args.merge_trace, "w", encoding="utf-8") as f:
            json.dump(events, f)
        report["merged_trace"] = {"path": args.merge_trace,
                                  "events": len(events),
                                  "lanes": ["{} pid{}".format(h, p)
                                            for _, h, p in lanes]}
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True, default=str))
    else:
        print(format_report(report))
        if args.merge_trace:
            print("merged trace: {} ({} events, {} lane(s))".format(
                args.merge_trace, len(events), len(lanes)))
    firing = bool(report.get("alerts", {}).get("firing"))
    return 0 if report["health"]["ok"] and not firing else 2


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("dir", help="dataset/output dir containing .telemetry/")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report (exit 2 when "
                         "unhealthy, same as the text mode)")
    ap.add_argument("--watch", action="store_true",
                    help="re-render the report every --interval seconds "
                         "until interrupted")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="--watch refresh period")
    ap.add_argument("--stall-ttl", type=float, default=None,
                    help="heartbeat age (s) after which a non-closed host "
                         "is declared stalled (default: the max TTL the "
                         "hosts advertised, else 30)")
    ap.add_argument("--wedge-window", type=float, default=None,
                    help="no-progress window (s) after which live hosts "
                         "with pending work are declared wedged "
                         "(default: max(4*stall_ttl, 120))")
    ap.add_argument("--window", type=float, default=None, metavar="SECONDS",
                    help="also read the series segments and report "
                         "windowed rates, sparklines, and gauge trends "
                         "over the trailing SECONDS")
    ap.add_argument("--alerts", default=None, metavar="RULES_FILE",
                    help="evaluate a JSON/TOML alert-rules file against "
                         "the rollup; any firing rule forces exit 2 and "
                         "transitions are journaled under .telemetry/")
    ap.add_argument("--merge-trace", default=None, metavar="OUT.json",
                    help="also write one clock-aligned Chrome trace "
                         "merging every host spool (open in Perfetto)")
    args = ap.parse_args(argv)
    if not args.watch:
        return run_once(args)
    try:
        while True:
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            run_once(args)
            sys.stdout.flush()
            time.sleep(max(args.interval, 0.2))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
