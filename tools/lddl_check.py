"""lddl-check: run the determinism / SPMD-safety analyzer over the tree.

Usage::

    python -m tools.lddl_check                      # lddl_tpu tools benchmarks
    python -m tools.lddl_check lddl_tpu --json      # machine-readable
    python -m tools.lddl_check --list-rules
    python -m tools.lddl_check --write-baseline     # regenerate grandfather
                                                    # file (then fill in the
                                                    # "reason" fields!)

Exit status: 0 when every finding is baselined or inline-suppressed,
1 when new findings (or syntax errors) exist, 2 on usage errors.
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root, for direct execution

from lddl_tpu import analysis  # noqa: E402

DEFAULT_PATHS = ("lddl_tpu", "tools", "benchmarks")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="lddl_check",
        description="AST-based determinism & SPMD-safety analyzer")
    parser.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                        help="files or directories (repo-relative); "
                             "default: %(default)s")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON report instead of text")
    parser.add_argument("--baseline",
                        default=os.path.join(analysis.REPO_ROOT,
                                             analysis.DEFAULT_BASELINE),
                        help="baseline file (empty string disables)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "(reasons for pre-existing entries are kept)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run (default all)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in analysis.all_rules():
            print("{:22s} {}".format(rule.id, rule.doc))
        return 0

    try:
        rules = analysis.get_rules(
            [r.strip() for r in args.rules.split(",")] if args.rules
            else None)
    except ValueError as e:
        parser.error(str(e))

    if args.write_baseline and (args.rules
                                or sorted(args.paths)
                                != sorted(DEFAULT_PATHS)):
        # A filtered run sees only a subset of findings; rewriting the
        # baseline from it would silently drop every grandfathered entry
        # outside the filter.
        parser.error("--write-baseline requires a full run: drop --rules "
                     "and explicit paths")

    try:
        report = analysis.run_check(args.paths, rules=rules,
                                    baseline_path=args.baseline or "")
    except FileNotFoundError as e:
        parser.error(str(e))

    if args.write_baseline:
        old = {(e.get("rule"), e.get("path"), e.get("match")):
               e.get("reason", "") for e in
               analysis.load_baseline(args.baseline)}
        entries = []
        for f in report.new + report.baselined:
            entry = analysis.baseline_entry(
                f, old.get(f.key(), "TODO: justify or fix"))
            if entry not in entries:
                entries.append(entry)
        entries.sort(key=lambda e: (e["path"], e["rule"], e["match"]))
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump({"entries": entries}, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print("wrote {} baseline entr{} to {}".format(
            len(entries), "y" if len(entries) == 1 else "ies",
            args.baseline))
        return 0

    if args.json:
        json.dump(report.to_dict(), sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for f in report.new:
            print(f.format())
        for path, msg in report.errors:
            print("{}:1: [parse-error] {}".format(path, msg))
        print("lddl-check: {} file(s), {} new finding(s), {} baselined, "
              "{} suppressed".format(report.files, len(report.new),
                                     len(report.baselined),
                                     len(report.suppressed)))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
