"""lddl-check: run the determinism / SPMD-safety analyzer over the tree.

Usage::

    python -m tools.lddl_check                      # lddl_tpu tools benchmarks
    python -m tools.lddl_check lddl_tpu --json      # machine-readable
    python -m tools.lddl_check --sarif out.sarif    # code-review artifact
    python -m tools.lddl_check --changed-only       # report only files
                                                    # changed vs git HEAD
                                                    # (analysis still spans
                                                    # the whole tree)
    python -m tools.lddl_check --list-rules
    python -m tools.lddl_check --write-baseline     # regenerate grandfather
                                                    # file (then fill in the
                                                    # "reason" fields!)

The interprocedural flow rules need the whole-tree project model; per-file
artifacts (AST findings + dataflow summaries) cache by content hash in
``.lddl_check_cache.json`` so warm runs only re-analyze edited files
(``--no-cache`` disables).

Exit status: 0 when every finding is baselined or inline-suppressed,
1 when new findings (or syntax errors) exist, 2 on usage errors.
"""

import argparse
import json
import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root, for direct execution

from lddl_tpu import analysis  # noqa: E402

DEFAULT_PATHS = ("lddl_tpu", "tools", "benchmarks")


def changed_python_files(root):
    """Repo-relative .py paths changed vs HEAD (staged, unstaged, and
    untracked), for ``--changed-only``. Returns None when git is
    unavailable (callers fall back to a full report)."""
    try:
        # -uall lists files INSIDE untracked directories (plain
        # --porcelain collapses a new package to "?? newdir/", whose
        # entry would fail the .py filter and hide every file in it).
        out = subprocess.run(
            ["git", "status", "--porcelain", "--no-renames",
             "--untracked-files=all"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    changed = set()
    for line in out.stdout.splitlines():
        path = line[3:].strip()
        if path.endswith(".py"):
            changed.add(path.replace(os.sep, "/"))
    return changed


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="lddl_check",
        description="AST-based determinism & SPMD-safety analyzer")
    parser.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                        help="files or directories (repo-relative); "
                             "default: %(default)s")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON report instead of text")
    parser.add_argument("--sarif", default=None, metavar="PATH",
                        help="also write a SARIF 2.1.0 report to PATH "
                             "('-' for stdout)")
    parser.add_argument("--changed-only", action="store_true",
                        help="report findings only for files changed vs "
                             "git HEAD (the analysis itself still spans "
                             "all given paths so cross-file flows stay "
                             "sound)")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable the content-hash AST+summary cache")
    parser.add_argument("--baseline",
                        default=os.path.join(analysis.REPO_ROOT,
                                             analysis.DEFAULT_BASELINE),
                        help="baseline file (empty string disables)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "(reasons for pre-existing entries are kept)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run (default all)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in analysis.all_rules():
            print("{:22s} {}".format(rule.id, rule.doc))
        return 0

    try:
        rules = analysis.get_rules(
            [r.strip() for r in args.rules.split(",")] if args.rules
            else None)
    except ValueError as e:
        parser.error(str(e))

    if args.write_baseline and (args.rules or args.changed_only
                                or sorted(args.paths)
                                != sorted(DEFAULT_PATHS)):
        # A filtered run sees only a subset of findings; rewriting the
        # baseline from it would silently drop every grandfathered entry
        # outside the filter.
        parser.error("--write-baseline requires a full run: drop --rules, "
                     "--changed-only, and explicit paths")

    report_paths = None
    if args.changed_only:
        changed = changed_python_files(analysis.REPO_ROOT)
        if changed is not None:
            report_paths = changed
            if not changed:
                print("lddl-check: no changed .py files vs HEAD")
                return 0
        else:
            print("lddl-check: git unavailable; --changed-only falling "
                  "back to a full report", file=sys.stderr)

    cache_path = None if args.no_cache else os.path.join(
        analysis.REPO_ROOT, analysis.DEFAULT_CACHE)
    try:
        report = analysis.run_check(args.paths, rules=rules,
                                    baseline_path=args.baseline or "",
                                    cache_path=cache_path,
                                    report_paths=report_paths)
    except FileNotFoundError as e:
        parser.error(str(e))

    if args.write_baseline:
        old = {(e.get("rule"), e.get("path"), e.get("match")):
               e.get("reason", "") for e in
               analysis.load_baseline(args.baseline)}
        counts = {}
        for f in report.new + report.baselined:
            counts[f.key()] = counts.get(f.key(), 0) + 1
        entries = [
            analysis.baseline_entry(
                next(f for f in report.new + report.baselined
                     if f.key() == key),
                old.get(key, "TODO: justify or fix"), count=n)
            for key, n in counts.items()
        ]
        entries.sort(key=lambda e: (e["path"], e["rule"], e["match"]))
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump({"entries": entries}, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print("wrote {} baseline entr{} to {}".format(
            len(entries), "y" if len(entries) == 1 else "ies",
            args.baseline))
        return 0

    if args.sarif:
        payload = analysis.to_sarif(report, rules)
        if args.sarif == "-":
            json.dump(payload, sys.stdout, indent=2)
            sys.stdout.write("\n")
        else:
            with open(args.sarif, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, indent=2)
                fh.write("\n")

    if args.json:
        json.dump(report.to_dict(), sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for f in report.new:
            print(f.format())
        for path, msg in report.errors:
            print("{}:1: [parse-error] {}".format(path, msg))
        print("lddl-check: {} file(s) ({} cached), {} new finding(s), "
              "{} baselined, {} suppressed in {:.2f}s".format(
                  report.files, report.files_cached, len(report.new),
                  len(report.baselined), len(report.suppressed),
                  report.elapsed_s))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
